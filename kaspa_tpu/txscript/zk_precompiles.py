"""ZK proof precompiles for the script engine (Toccata surface).

Reference: crypto/txscript/src/zk_precompiles/ — OpZkPrecompile (0xa6)
pops a tag byte and dispatches:

- Groth16 (tag 0x20): full BN254 verification via crypto/bn254.py,
  matching arkworks ark-groth16 semantics bit-for-bit: compressed VK /
  proof deserialization with trailing-byte and canonicity checks, arity
  check *before* the per-gamma_abc metering charge, prepared-input
  accumulation, and the 4-pairing product equation.
- RISC0 succinct (tag 0x21): stack protocol, strict operand parsing,
  control-inclusion Merkle structure and the ReceiptClaim binding hash
  chain (risc0_binfmt tagged-struct hashing — golden-tested against the
  reference's succinct.* fixtures).  The STARK seal check itself requires
  the risc0 recursion-circuit definition (a generated constraint system
  the reference consumes as the `risc0-circuit-recursion` crate); it is
  not reproducible from spec here, so seal verification reports
  `R0Error("succinct seal verification unavailable")` and the script
  fails closed.  Tag parsing, pricing, claim binding and all structural
  rejections match the reference.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from kaspa_tpu.crypto import bn254
from kaspa_tpu.txscript.resource_meter import MeterError

SCRIPT_UNITS_PER_GRAM = 100  # consensus/core/src/mass/units.rs:6

# tags.rs: supported proof systems and their script-unit prices
TAG_GROTH16 = 0x20
TAG_R0_SUCCINCT = 0x21
TAG_COSTS = {
    TAG_GROTH16: 1000 * 140 * SCRIPT_UNITS_PER_GRAM,
    TAG_R0_SUCCINCT: 1000 * 250 * SCRIPT_UNITS_PER_GRAM,
}
MAX_TAG_COST = max(TAG_COSTS.values())

# groth16/mod.rs:18 — per gamma_abc_g1 element VK deserialization price
GROTH16_GAMMA_ABC_G1_ELEMENT_SCRIPT_UNITS = 250_000

FR_BYTES = 32


class ZkError(Exception):
    """TxScriptError::ZkIntegrity equivalents."""


def parse_tag(tag_bytes: bytes) -> int:
    if len(tag_bytes) == 0:
        raise ZkError("Tag byte is missing")
    if len(tag_bytes) != 1:
        raise ZkError(f"Tag byte length {len(tag_bytes)} is invalid")
    tag = tag_bytes[0]
    if tag not in TAG_COSTS:
        raise ZkError(f"Unknown ZK tag {tag:#x}")
    return tag


def compute_zk_cost(tag: int) -> int:
    """Static upper-bound pricing for mass commitments (unknown tags price
    at the max so a commitment can never undershoot)."""
    return TAG_COSTS.get(tag, MAX_TAG_COST)


# ----------------------------------------------------------------------
# Groth16
# ----------------------------------------------------------------------


def _read_g1(buf: bytes, off: int, validate: bool = True):
    if len(buf) - off < 32:
        raise ZkError("truncated G1 element")
    pt = bn254.g1_deserialize_compressed(buf[off : off + 32], validate=validate)
    return pt, off + 32


def _read_g2(buf: bytes, off: int):
    if len(buf) - off < 64:
        raise ZkError("truncated G2 element")
    pt = bn254.g2_deserialize_compressed(buf[off : off + 64])
    return pt, off + 64


def deserialize_verifying_key_with_metering(vk_bytes: bytes, public_input_count: int, meter):
    """Mirrors groth16/mod.rs deserialize_verifying_key_with_metering:
    arity is checked before gamma_abc is priced or read."""
    try:
        off = 0
        alpha_g1, off = _read_g1(vk_bytes, off)
        beta_g2, off = _read_g2(vk_bytes, off)
        gamma_g2, off = _read_g2(vk_bytes, off)
        delta_g2, off = _read_g2(vk_bytes, off)
    except bn254.DeserializeError as e:
        raise ZkError(f"invalid verifying key: {e}") from e
    if len(vk_bytes) - off < 8:
        raise ZkError("truncated gamma_abc count")
    count = int.from_bytes(vk_bytes[off : off + 8], "little")
    off += 8
    if count == 0:
        raise ZkError("verifying key has empty gamma_abc_g1")
    if public_input_count + 1 != count:
        raise ZkError("public input arity mismatch")
    meter.consume_script_units(count * GROTH16_GAMMA_ABC_G1_ELEMENT_SCRIPT_UNITS)
    gamma_abc = []
    try:
        for _ in range(count):
            # Validate::No on read, then a batch on-curve check (G1 cofactor
            # is 1, so curve membership is subgroup membership)
            pt, off = _read_g1(vk_bytes, off, validate=False)
            gamma_abc.append(pt)
    except bn254.DeserializeError as e:
        raise ZkError(f"invalid gamma_abc element: {e}") from e
    if off != len(vk_bytes):
        raise ZkError("trailing verifying key bytes")
    for pt in gamma_abc:
        if not bn254.g1_is_on_curve(pt):
            raise ZkError("gamma_abc element not on curve")
    return alpha_g1, beta_g2, gamma_g2, delta_g2, gamma_abc


def deserialize_proof(proof_bytes: bytes):
    try:
        off = 0
        a, off = _read_g1(proof_bytes, off)
        b, off = _read_g2(proof_bytes, off)
        c, off = _read_g1(proof_bytes, off)
    except bn254.DeserializeError as e:
        raise ZkError(f"invalid proof: {e}") from e
    if off != len(proof_bytes):
        raise ZkError("trailing proof bytes")
    return a, b, c


def parse_fr(b: bytes) -> int:
    if len(b) != FR_BYTES:
        raise ZkError(f"Invalid Fr length {len(b)}")
    try:
        return bn254.fr_deserialize(b)
    except bn254.DeserializeError as e:
        raise ZkError(f"invalid Fr: {e}") from e


def groth16_verify(dstack: list, meter) -> None:
    """Stack (top first): vk bytes, proof bytes, input count i32, inputs...
    (groth16/mod.rs verify_zk).  Pops operands; raises ZkError/MeterError
    on any failure."""
    from kaspa_tpu.txscript.vm import TxScriptError, deserialize_i64

    if len(dstack) < 3:
        raise ZkError("missing Groth16 operands")
    vk_bytes = dstack.pop()
    proof_bytes = dstack.pop()
    try:
        n_inputs = deserialize_i64(dstack.pop(), enforce_minimal=True, max_len=4)
    except TxScriptError as e:
        raise ZkError(str(e)) from e
    if n_inputs < 0:
        raise ZkError("negative public input count")
    inputs = []
    for _ in range(n_inputs):
        if not dstack:
            raise ZkError("missing public input")
        inputs.append(parse_fr(dstack.pop()))

    alpha_g1, beta_g2, gamma_g2, delta_g2, gamma_abc = deserialize_verifying_key_with_metering(
        vk_bytes, len(inputs), meter
    )
    a, b, c = deserialize_proof(proof_bytes)

    # prepared inputs: L = gamma_abc[0] + sum_i input_i * gamma_abc[i+1]
    acc = gamma_abc[0]
    for scalar, base in zip(inputs, gamma_abc[1:]):
        acc = bn254.g1_add(acc, bn254.g1_mul(base, scalar))

    # e(A, B) == e(alpha, beta) * e(L, gamma) * e(C, delta)
    ok = bn254.multi_pairing(
        [
            (bn254.g1_neg(a), b),
            (alpha_g1, beta_g2),
            (acc, gamma_g2),
            (c, delta_g2),
        ]
    )
    if not ok:
        raise ZkError("Groth16 verification failed")


# ----------------------------------------------------------------------
# RISC0 succinct receipts
# ----------------------------------------------------------------------

DIGEST_BYTES = 32

HASHFN_BLAKE2B = 0
HASHFN_POSEIDON2 = 1
HASHFN_SHA256 = 2

POSEIDON2_CONTROL_MERKLE_DEPTH = 8


class R0Error(Exception):
    pass


def parse_digest(b: bytes) -> bytes:
    if len(b) != DIGEST_BYTES:
        raise R0Error(f"invalid digest length {len(b)}")
    return bytes(b)


def parse_seal(b: bytes) -> list[int]:
    if len(b) % 4 != 0:
        raise R0Error(f"invalid seal length {len(b)}")
    return [int.from_bytes(b[i : i + 4], "little") for i in range(0, len(b), 4)]


def parse_hashfn(b: bytes) -> int:
    if len(b) != 1:
        raise R0Error(f"invalid hashfn encoding length {len(b)}")
    if b[0] not in (HASHFN_BLAKE2B, HASHFN_POSEIDON2, HASHFN_SHA256):
        raise R0Error(f"invalid hashfn id {b[0]}")
    return b[0]


def parse_merkle_index(b: bytes) -> int:
    if len(b) != 4:
        raise R0Error(f"invalid merkle index length {len(b)}")
    return int.from_bytes(b, "little")


def parse_digest_list(b: bytes) -> list[bytes]:
    if len(b) % DIGEST_BYTES != 0:
        raise R0Error(f"invalid digest list length {len(b)}")
    return [bytes(b[i : i + DIGEST_BYTES]) for i in range(0, len(b), DIGEST_BYTES)]


@dataclass
class MerkleProof:
    """Control-ID inclusion proof (risc0/merkle.rs): fold sibling digests
    from the leaf by the index's bit path."""

    index: int
    digests: list

    def root(self, leaf: bytes, hash_pair) -> bytes:
        cur = leaf
        idx = self.index
        for sibling in self.digests:
            cur = hash_pair(cur, sibling) if idx & 1 == 0 else hash_pair(sibling, cur)
            idx >>= 1
        return cur


# --- risc0_binfmt tagged-struct hashing (the claim binding chain) ---


def tagged_struct(tag: str, down: list[bytes], data: list[int]) -> bytes:
    """sha256(sha256(tag) || down_digests || data_u32s_le || len(down) as
    u16 le) — risc0_binfmt's Merkle-ized struct digest."""
    buf = hashlib.sha256(tag.encode()).digest()
    for d in down:
        buf += d
    for w in data:
        buf += (w & 0xFFFFFFFF).to_bytes(4, "little")
    buf += (len(down) & 0xFFFF).to_bytes(2, "little")
    return hashlib.sha256(buf).digest()


def system_state_digest(pc: int, merkle_root: bytes) -> bytes:
    return tagged_struct("risc0.SystemState", [merkle_root], [pc])


def output_digest(journal: bytes, assumptions: bytes) -> bytes:
    return tagged_struct("risc0.Output", [journal, assumptions], [])


def receipt_claim_digest(pre: bytes, post: bytes, input_: bytes, output: bytes, sys_exit: int, user_exit: int) -> bytes:
    return tagged_struct("risc0.ReceiptClaim", [input_, pre, post, output], [sys_exit, user_exit])


ZERO_DIGEST = b"\x00" * DIGEST_BYTES


def compute_assert_claim(claim: bytes, image_id: bytes, journal_hash: bytes) -> None:
    """receipt_claim.rs compute_assert_claim: the claim digest must equal
    that of a Halted(0) execution of `image_id` committing `journal_hash`
    — binding the proof to the exact program and output."""
    computed = receipt_claim_digest(
        pre=image_id,
        post=system_state_digest(0, ZERO_DIGEST),
        input_=ZERO_DIGEST,
        output=output_digest(journal_hash, ZERO_DIGEST),
        sys_exit=0,  # ExitCode::Halted -> (0, user_exit)
        user_exit=0,
    )
    if claim != computed:
        raise R0Error("claim binding verification failed")


def r0_succinct_verify(dstack: list, meter) -> None:
    """Stack (top first): hashfn, control_id, image_id, journal, seal,
    control_digests, control_index, claim (risc0/mod.rs verify_zk).

    Operand parsing, hashfn gating, inclusion-proof bounds and claim
    binding follow the reference exactly.  The seal STARK check needs the
    risc0 recursion-circuit constraint system (not reproducible from
    spec); reaching it raises — the precompile fails closed."""
    if len(dstack) < 8:
        raise R0Error("missing R0 succinct operands")
    hashfn_b = dstack.pop()
    control_id_b = dstack.pop()
    image_id_b = dstack.pop()
    journal_b = dstack.pop()
    seal_b = dstack.pop()
    control_digests_b = dstack.pop()
    control_index_b = dstack.pop()
    claim_b = dstack.pop()

    control_id = parse_digest(control_id_b)
    seal = parse_seal(seal_b)
    claim = parse_digest(claim_b)
    hashfn = parse_hashfn(hashfn_b)
    if hashfn != HASHFN_POSEIDON2:
        raise R0Error(f"unsupported hashfn {hashfn}")
    control_index = parse_merkle_index(control_index_b)
    control_digests = parse_digest_list(control_digests_b)
    if len(control_digests) > POSEIDON2_CONTROL_MERKLE_DEPTH:
        raise R0Error(
            f"control inclusion proof too long: {len(control_digests)} > {POSEIDON2_CONTROL_MERKLE_DEPTH}"
        )
    image_id = parse_digest(image_id_b)
    journal = parse_digest(journal_b)

    # bind the claim before touching the seal so tampered image/journal
    # fail with the precise claim error
    compute_assert_claim(claim, image_id, journal)

    _ = (seal, control_id, MerkleProof(control_index, control_digests))
    raise R0Error(
        "succinct seal verification unavailable: requires the risc0 "
        "recursion-circuit definition (risc0-circuit-recursion)"
    )


# ----------------------------------------------------------------------
# dispatch (zk_precompiles/mod.rs verify_zk)
# ----------------------------------------------------------------------


def verify_zk(tag: int, dstack: list, meter) -> None:
    if tag == TAG_GROTH16:
        groth16_verify(dstack, meter)
    elif tag == TAG_R0_SUCCINCT:
        r0_succinct_verify(dstack, meter)
    else:  # parse_tag already rejects unknown tags
        raise ZkError(f"Unknown ZK tag {tag:#x}")
