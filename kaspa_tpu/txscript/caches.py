"""Signature cache (reference: crypto/txscript/src/caches.rs:14-55).

Bounded map keyed by (sig, msg, pubkey, kind) with random eviction, exactly
like the reference's IndexMap+swap_remove scheme (the reference wraps it in
a RwLock; here a plain Lock — the parallel VM fallback lane reads and
writes it from pool threads, and the multi-step eviction must stay atomic).
Shared across the validator so repeated relay/mempool/block validations of
the same signature skip the device round-trip.
"""

from __future__ import annotations

import random
import threading

from kaspa_tpu.utils.sync import ranked_lock


class SigCache:
    def __init__(self, size: int = 10_000, seed: int | None = None):
        assert size > 0
        self.size = size
        self._map: dict[tuple, bool] = {}
        self._keys: list[tuple] = []
        self._rng = random.Random(seed)
        self._lock = ranked_lock("txscript.cache")
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple):
        with self._lock:
            v = self._map.get(key)
            if v is None:
                self.misses += 1
            else:
                self.hits += 1
            return v

    def insert(self, key: tuple, value: bool) -> None:
        with self._lock:
            if key in self._map:
                self._map[key] = value
                return
            if len(self._keys) == self.size:
                # random eviction with swap-remove (caches.rs:46-55)
                i = self._rng.randrange(self.size)
                old = self._keys[i]
                del self._map[old]
                self._keys[i] = self._keys[-1]
                self._keys.pop()
            self._keys.append(key)
            self._map[key] = value
