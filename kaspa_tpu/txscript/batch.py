"""Batched script checking: the TPU offload point.

The reference validates scripts per input inside rayon par_iter
(tx_validation_in_utxo_context.rs:206-223); here the per-input signature
checks of an entire block/mergeset are *collected* into one device batch:

    collect phase  : classify each (input, utxo) pair, compute its sighash
                     (host, memoized per tx), queue (pubkey, msg, sig)
    dispatch phase : one batched Schnorr kernel call + one ECDSA call,
                     overlapped with the host-VM fallback lane
    resolve phase  : validity bitmask mapped back to per-input results

Consensus equivalence: only canonical standard P2PK spends take the batch
path; anything else routes to the host VM (txscript.vm) — same acceptance
decisions as running the reference's engine per input.

The VM fallback lane is *deferred and parallel*: nonstandard inputs are
queued at collect time and executed at dispatch on a bounded thread pool,
concurrently with the device batches (the device dispatch releases the GIL
while XLA runs, so a multisig/P2SH-heavy block no longer serializes the
fallback work behind — or in front of — the device lane).  Failure
precedence matches the serial path exactly: VM failures apply first, in
collect order, then device-batch failures in queue order, so the
(token -> first error) mapping is bit-identical to serial execution.
"""

from __future__ import annotations

import functools
import os
import threading

from kaspa_tpu.utils.sync import ranked_lock
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from time import perf_counter_ns

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.crypto import secp
from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import REGISTRY, SIZE_BUCKETS
from kaspa_tpu.resilience.faults import FAULTS, FaultInjected
from kaspa_tpu.txscript import standard
from kaspa_tpu.txscript.caches import SigCache

# fast-path vs fallback mix: a fallback-heavy workload starves the device
# batch, which is the first thing to check when occupancy drops
_JOBS = REGISTRY.counter_family("txscript_batch_jobs", "kind", help="signature jobs queued for device dispatch")
_SIGCACHE_SKIPS = REGISTRY.counter("txscript_batch_sigcache_skips", help="jobs answered by the sig cache pre-dispatch")
_VM_FALLBACKS = REGISTRY.counter("txscript_vm_fallbacks", help="inputs routed to the host VM instead of the batch")
_FALLBACK_BATCH = REGISTRY.histogram(
    "txscript_fallback_batch_size", SIZE_BUCKETS, help="deferred VM fallback jobs per dispatch"
)
_VM_RETRIES = REGISTRY.counter(
    "txscript_vm_fault_retries", help="VM fallback jobs retried after an injected transient fault"
)


def _default_fallback_workers() -> int:
    """Bounded pool width for the VM fallback lane (0/1 = serial)."""
    raw = os.environ.get("KASPA_TPU_VM_FALLBACK_WORKERS")
    if raw is not None:
        return max(0, int(raw))
    return max(2, min(8, os.cpu_count() or 2))


_pool_lock = ranked_lock("txscript.pool")
_pool: ThreadPoolExecutor | None = None


def _fallback_pool() -> ThreadPoolExecutor:
    """Shared bounded executor (threads are reused across dispatches and
    across checkers; daemonized so interpreter shutdown never hangs)."""
    global _pool
    if _pool is None:
        with _pool_lock:
            if _pool is None:
                _pool = ThreadPoolExecutor(
                    max_workers=_default_fallback_workers() or 1, thread_name_prefix="vm-fallback"
                )
    return _pool


class ScriptCheckError(Exception):
    def __init__(self, msg: str, input_index: int | None = None):
        super().__init__(msg)
        self.input_index = input_index


@dataclass
class _Job:
    kind: str  # "schnorr" | "ecdsa"
    pubkey: bytes
    msg: bytes
    sig: bytes
    cache_key: tuple
    callback: object  # fn(bool)


@dataclass
class _FallbackJob:
    token: int
    input_index: int
    run: object  # fn() -> None, raises on invalid script
    # collector's TraceContext + enqueue stamp: pool threads re-attach the
    # VM execution (and its queue wait) to the owning block's trace
    ctx: object = None
    enqueued_ns: int = 0


def _run_fallback(job: _FallbackJob) -> Exception | None:
    """Execute one deferred VM job; returns the failure (or None).

    Runs on pool threads: the engine instance is job-local; the shared
    SigCache is internally locked; SigHashReusedValues memoization races
    are benign (idempotent writes of identical digests).

    An injected ``vm.fallback.exec`` fault is a *transient infrastructure*
    failure, not a script verdict: the job retries, so fault schedules can
    never flip a consensus decision (the sustain run's sink-identity check
    depends on this).
    """
    t0 = perf_counter_ns()
    if job.enqueued_ns:
        trace.record_span("wait.vm", job.ctx, job.enqueued_ns, t0)
    with trace.span("vm.fallback", parent=job.ctx, input=job.input_index):
        while True:
            try:
                FAULTS.fire("vm.fallback.exec")
                job.run()
                return None
            except FaultInjected:
                _VM_RETRIES.inc()
                continue
            except Exception as e:  # noqa: BLE001 - VM raises on invalid script
                return e


# in-flight accounting for the shared pool so daemon shutdown can drain
# the deferred VM lane instead of abandoning futures mid-dispatch
_inflight_lock = ranked_lock("txscript.inflight")
_inflight = 0
_inflight_zero = threading.Event()
_inflight_zero.set()


def _submit_tracked(pool: ThreadPoolExecutor, job: _FallbackJob):
    global _inflight
    with _inflight_lock:
        _inflight += 1
        _inflight_zero.clear()

    def run():
        global _inflight
        try:
            return _run_fallback(job)
        finally:
            with _inflight_lock:
                _inflight -= 1
                if _inflight == 0:
                    _inflight_zero.set()

    return pool.submit(run)


def drain_fallback_pool(timeout: float = 10.0) -> bool:
    """Block until every in-flight deferred VM job has resolved (True) or
    the timeout expires (False).  Dispatchers joining their own futures is
    the common case; this is the daemon-shutdown barrier."""
    return _inflight_zero.wait(timeout)


def shutdown_fallback_pool(timeout: float = 10.0) -> bool:
    """Drain, then retire the shared executor (a later dispatch lazily
    rebuilds it).  Returns whether the drain completed in time."""
    global _pool
    drained = drain_fallback_pool(timeout)
    with _pool_lock:
        pool, _pool = _pool, None
    if pool is not None:
        pool.shutdown(wait=False)
    return drained


class BatchScriptChecker:
    """Collects signature-check jobs across many txs, dispatches once.

    ``fallback_workers``: width of the VM fallback lane (None = shared
    default pool, sized by KASPA_TPU_VM_FALLBACK_WORKERS or cpu count;
    0/1 = serial execution at dispatch — same results either way).

    ``traffic_class``: coalescing-queue traffic class for this checker's
    device submissions (e.g. ``"standalone_tx"`` for the ingest tier's
    admission batches).  Class-qualified kinds get their own coalesce
    target/age and counters in ops/dispatch; results are bit-identical.
    """

    def __init__(
        self,
        sig_cache: SigCache | None = None,
        vm_fallback=None,
        fallback_workers: int | None = None,
        traffic_class: str | None = None,
    ):
        self.sig_cache = sig_cache if sig_cache is not None else SigCache()
        # contract: fn(tx, entries, input_index, reused, pov_daa_score) — the
        # daa score drives fork-activation gating inside the engine
        self.vm_fallback = vm_fallback
        self.fallback_workers = fallback_workers
        self.traffic_class = traffic_class
        self._jobs: list[_Job] = []
        self._fallbacks: list[_FallbackJob] = []
        self._results: dict[int, Exception | None] = {}

    def collect_tx(self, token: int, tx, utxo_entries, reused=None, pov_daa_score=None, seq_commit_accessor=None) -> None:
        """Queue all input script checks of `tx`; result under `token`.
        ``pov_daa_score`` feeds fork-activation gating in the VM fallback;
        ``seq_commit_accessor`` backs OpChainblockSeqCommit post-Toccata."""
        if reused is None:
            reused = chash.SigHashReusedValues()
        self._results.setdefault(token, None)
        for i, (inp, entry) in enumerate(zip(tx.inputs, utxo_entries)):
            try:
                self._collect_input(token, tx, utxo_entries, i, inp, entry, reused, pov_daa_score, seq_commit_accessor)
            except ScriptCheckError as e:
                self._fail(token, e)

    def _fail(self, token: int, err: Exception) -> None:
        if self._results.get(token) is None:
            self._results[token] = err

    def _collect_input(self, token, tx, utxo_entries, i, inp, entry, reused, pov_daa_score=None, seq_commit_accessor=None):
        cls = standard.classify_script(entry.script_public_key)
        if cls in (standard.ScriptClass.PUB_KEY, standard.ScriptClass.PUB_KEY_ECDSA):
            # runtime sig-op parity with the engine path (lib.rs:545 + :898):
            # the single CheckSig consumes one committed sig op
            commit = inp.compute_commit
            if commit.sig_op_count() is not None and commit.sig_op_count() < 1:
                raise ScriptCheckError("exceeded sig op limit of 0", i)
        if cls == standard.ScriptClass.PUB_KEY:
            data = standard.parse_single_push(inp.signature_script)
            if data is None or len(data) == 0:
                raise ScriptCheckError("signature script is not a canonical single push", i)
            if len(data) != 65:
                raise ScriptCheckError(f"invalid schnorr signature length {len(data) - 1}", i)
            sig, hash_type = data[:64], data[64]
            if hash_type not in chash.ALLOWED_SIG_HASH_TYPES:
                raise ScriptCheckError(f"invalid hash type {hash_type}", i)
            pubkey = entry.script_public_key.script[1:33]
            msg = chash.calc_schnorr_signature_hash(tx, utxo_entries, i, hash_type, reused)
            self._queue(token, "schnorr", pubkey, msg, sig, i)
        elif cls == standard.ScriptClass.PUB_KEY_ECDSA:
            data = standard.parse_single_push(inp.signature_script)
            if data is None or len(data) == 0:
                raise ScriptCheckError("signature script is not a canonical single push", i)
            if len(data) != 65:
                raise ScriptCheckError(f"invalid ecdsa signature length {len(data) - 1}", i)
            sig, hash_type = data[:64], data[64]
            if hash_type not in chash.ALLOWED_SIG_HASH_TYPES:
                raise ScriptCheckError(f"invalid hash type {hash_type}", i)
            pubkey = entry.script_public_key.script[1:34]
            msg = chash.calc_ecdsa_signature_hash(tx, utxo_entries, i, hash_type, reused)
            self._queue(token, "ecdsa", pubkey, msg, sig, i)
        else:
            # non-fast-path scripts defer to the host VM lane (executed at
            # dispatch, concurrently with the device batches)
            if self.vm_fallback is None:
                raise ScriptCheckError(f"unsupported script class {cls.value} (VM fallback not wired)", i)
            _VM_FALLBACKS.inc()
            self._fallbacks.append(
                _FallbackJob(
                    token,
                    i,
                    functools.partial(
                        self.vm_fallback, tx, utxo_entries, i, reused, pov_daa_score,
                        seq_commit_accessor=seq_commit_accessor,
                    ),
                    ctx=trace.context(),
                    enqueued_ns=perf_counter_ns(),
                )
            )

    def _queue(self, token, kind, pubkey, msg, sig, input_index):
        cache_key = (kind, sig, msg, pubkey)
        cached = self.sig_cache.get(cache_key)
        if cached is not None:
            _SIGCACHE_SKIPS.inc()
            if not cached:
                self._fail(token, ScriptCheckError("invalid signature (cached)", input_index))
            return
        _JOBS.inc(kind)

        # `fail` is supplied at resolve time: dispatch_async detaches the
        # results dict into its handle, so the callback must not close over
        # the checker's (reusable) live state
        def cb(ok: bool, fail, token=token, input_index=input_index):
            if not ok:
                fail(token, ScriptCheckError("invalid signature", input_index))

        self._jobs.append(_Job(kind, pubkey, msg, sig, cache_key, cb))

    def _effective_workers(self, jobs: int) -> int:
        w = self.fallback_workers if self.fallback_workers is not None else _default_fallback_workers()
        return min(w, jobs)

    def dispatch(self) -> dict[int, Exception | None]:
        """Run all queued checks: the VM fallback lane on the bounded pool
        overlapped with (at most) two device batches; returns
        token -> None (valid) | Exception (first failure)."""
        return self.dispatch_async().result()

    def dispatch_async(self) -> "DispatchHandle":
        """Submit all queued checks without blocking and detach the
        checker's state into the returned handle: the VM fallback lane
        goes to the bounded pool, the device lane to the cross-block
        coalescing queue (`ops/dispatch.py`) when enabled.  The checker is
        immediately reusable for the next collect round; the handle's
        ``result()`` yields the same token -> first-error mapping — and
        the same failure precedence — as the synchronous path."""
        fallbacks, self._fallbacks = self._fallbacks, []
        jobs, self._jobs = self._jobs, []
        results, self._results = self._results, {}

        pending = None
        if fallbacks:
            _FALLBACK_BATCH.observe(len(fallbacks))
            if self._effective_workers(len(fallbacks)) > 1:
                pool = _fallback_pool()
                pending = [_submit_tracked(pool, j) for j in fallbacks]

        schnorr = [j for j in jobs if j.kind == "schnorr"]
        ecdsa = [j for j in jobs if j.kind == "ecdsa"]
        from kaspa_tpu.ops import dispatch as coalesce

        engine = coalesce.active()
        tickets = None
        if engine is not None:
            # chunk ownership is donated to the coalescing queue: the item
            # lists are never touched again from this side.  A traffic class
            # qualifies the kind so the queue applies per-class batch
            # dynamics; the device call maps back to the base kernel.
            prefix = f"{self.traffic_class}:" if self.traffic_class else ""
            tickets = {}
            if schnorr:
                tickets["schnorr"] = engine.submit(
                    f"{prefix}schnorr", [(j.pubkey, j.msg, j.sig) for j in schnorr]
                )
            if ecdsa:
                tickets["ecdsa"] = engine.submit(
                    f"{prefix}ecdsa", [(j.pubkey, j.msg, j.sig) for j in ecdsa]
                )
        return DispatchHandle(self.sig_cache, fallbacks, pending, schnorr, ecdsa, tickets, results)


class DispatchHandle:
    """In-flight dispatch: owns the detached jobs/results of one round."""

    def __init__(self, sig_cache, fallbacks, pending, schnorr, ecdsa, tickets, results):
        self.sig_cache = sig_cache
        self._fallbacks = fallbacks
        self._pending = pending
        self._schnorr = schnorr
        self._ecdsa = ecdsa
        self._tickets = tickets  # None = coalescing disabled (sync device lane)
        self._results = results
        self._resolved = False

    def _fail(self, token: int, err: Exception) -> None:
        if self._results.get(token) is None:
            self._results[token] = err

    def result(self) -> dict[int, Exception | None]:
        """Join every lane; token -> None (valid) | Exception (first
        failure), bit-identical to the legacy synchronous dispatch."""
        if self._resolved:
            return self._results
        self._resolved = True
        schnorr_mask = ecdsa_mask = None
        if self._tickets is None:
            # legacy synchronous device lane (coalescing disabled)
            if self._schnorr:
                with trace.span("txscript.dispatch", kind="schnorr", jobs=len(self._schnorr)):
                    # verify_batch (not schnorr_verify_batch): the sync lane
                    # honors --verify-mode aggregate/auto like the coalesced one
                    schnorr_mask = secp.verify_batch("schnorr", [(j.pubkey, j.msg, j.sig) for j in self._schnorr])
            if self._ecdsa:
                with trace.span("txscript.dispatch", kind="ecdsa", jobs=len(self._ecdsa)):
                    ecdsa_mask = secp.verify_batch("ecdsa", [(j.pubkey, j.msg, j.sig) for j in self._ecdsa])

        # fallback lane resolution BEFORE the device callbacks: the serial
        # path ran the VM at collect time, so VM failures must win the
        # first-error slot over same-token batch failures, in collect order
        if self._fallbacks:
            with trace.span("txscript.fallback_join", jobs=len(self._fallbacks), parallel=self._pending is not None):
                errors = (
                    [f.result() for f in self._pending]
                    if self._pending is not None
                    else [_run_fallback(j) for j in self._fallbacks]
                )
            for job, err in zip(self._fallbacks, errors):
                if err is not None:
                    self._fail(job.token, ScriptCheckError(str(err), job.input_index))

        if self._tickets is not None:
            # coalesced device lane: block on this round's tickets (wait()
            # nudges the queue, so a serial caller flushes immediately)
            with trace.span("txscript.dispatch_wait", kinds=",".join(sorted(self._tickets))):
                try:
                    if "schnorr" in self._tickets:
                        schnorr_mask = self._tickets["schnorr"].wait()
                    if "ecdsa" in self._tickets:
                        ecdsa_mask = self._tickets["ecdsa"].wait()
                except TimeoutError as e:
                    # infrastructure failure, not a consensus verdict: keep
                    # the TimeoutError type but attach this handle's view
                    if hasattr(e, "add_note"):
                        e.add_note(
                            "batch handle: "
                            f"schnorr_jobs={len(self._schnorr)} ecdsa_jobs={len(self._ecdsa)} "
                            f"fallback_jobs={len(self._fallbacks)} tokens={len(self._results)}"
                        )
                    raise

        for jobs, mask in ((self._schnorr, schnorr_mask), (self._ecdsa, ecdsa_mask)):
            if mask is not None:
                for j, ok in zip(jobs, mask):
                    self.sig_cache.insert(j.cache_key, bool(ok))
                    j.callback(bool(ok), self._fail)
        return self._results
