"""Shared sender pool: N worker threads drain M subscriber queues.

The daemon's historical shape is one sender thread per ``Subscriber`` —
fine for tens of RPC clients, impossible for the 50k-virtual-subscriber
load harness (and the ROADMAP's million-subscriber target).  A
``SenderPool`` inverts that: subscribers become passive bounded queues
and a small fixed crew of workers delivers for whichever subscribers
have pending events.

Scheduling contract (with ``Subscriber`` in broadcaster.py):

* ``Subscriber.offer`` sets ``_scheduled`` under the subscriber lock the
  first time the queue goes non-empty and calls ``pool.schedule(sub)``
  AFTER releasing it — each subscriber sits in the ready queue at most
  once, so the queue is bounded by the subscriber population.
* A worker pops a subscriber and calls ``sub._pool_drain(batch)``, which
  delivers up to ``batch`` events.  If events remain the worker re-queues
  the subscriber (round-robin fairness: a firehose subscriber cannot
  starve the rest); if the queue drained, ``_pool_drain`` clears
  ``_scheduled`` under the subscriber lock so the next ``offer`` re-kicks.

Lock order is broadcaster(50) -> pool(52) -> subscriber(55); ``schedule``
is always called lock-free or under the subscriber lock's CALLER (never
inside it), and workers take the pool queue's lock and the subscriber
lock strictly in rank order.
"""

from __future__ import annotations

import queue
import threading

from kaspa_tpu.core.log import get_logger
from kaspa_tpu.observability.core import REGISTRY

log = get_logger("serving")

_POOL_ROUNDS = REGISTRY.counter(
    "serving_pool_drain_rounds", help="subscriber drain rounds executed by sender-pool workers"
)
_POOL_RESCHEDULES = REGISTRY.counter(
    "serving_pool_reschedules", help="drain rounds that hit the fairness batch limit and re-queued the subscriber"
)

# Safety valve far above any realistic subscriber population; the
# scheduled-flag contract bounds live entries to one per subscriber.
_READY_MAXSIZE = 1 << 20


class SenderPool:
    """Fixed crew of sender threads shared by many pooled Subscribers."""

    def __init__(self, workers: int = 2, batch: int = 64, name: str = "serving-pool"):
        self.workers = max(1, int(workers))
        self.batch = max(1, int(batch))
        self._ready: queue.Queue = queue.Queue(maxsize=_READY_MAXSIZE)
        self._stopping = False
        self._threads = [
            threading.Thread(target=self._work, daemon=True, name=f"{name}-{i}")
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()

    # --- subscriber side (called by Subscriber.offer / workers) ---

    def schedule(self, sub) -> None:
        """Queue a subscriber for draining.  The caller guarantees the
        at-most-once invariant via the subscriber's ``_scheduled`` flag."""
        try:
            self._ready.put_nowait(sub)
        except queue.Full:  # pragma: no cover - means >1M live subscribers
            # deliver inline rather than strand the subscriber with its
            # _scheduled flag set and nobody coming
            log.error("sender-pool ready queue overflow; draining %s inline", sub.name)
            while sub._pool_drain(self.batch):
                pass

    def schedule_many(self, subs) -> None:
        """Queue a routed event's worth of subscribers in chunks: one
        ready-queue entry (one worker wakeup) per ``batch`` subscribers
        instead of one per subscriber — the sharded fanout workers kick
        their whole matched set this way after offering outside the shard
        lock.  The at-most-once invariant is the caller's, same as
        ``schedule``."""
        subs = list(subs)
        for i in range(0, len(subs), self.batch):
            chunk = subs[i : i + self.batch]
            try:
                self._ready.put_nowait(chunk)
            except queue.Full:  # pragma: no cover - same valve as schedule()
                log.error("sender-pool ready queue overflow; draining %d subscribers inline", len(chunk))
                for sub in chunk:
                    while sub._pool_drain(self.batch):
                        pass

    def pending(self) -> int:
        """Subscribers currently queued for a drain round."""
        return self._ready.qsize()

    # --- worker loop ---

    def _work(self) -> None:
        while True:
            item = self._ready.get()
            if item is None:
                return
            if isinstance(item, list):
                _POOL_ROUNDS.inc(len(item))  # one inc per chunk, not per sub
            else:
                _POOL_ROUNDS.inc()
                item = (item,)
            for sub in item:
                try:
                    more = sub._pool_drain(self.batch)
                except Exception:  # noqa: BLE001 - one bad subscriber must not kill the crew
                    log.exception("sender-pool drain failed for %s", sub.name)
                    with sub._lock:
                        sub._scheduled = False
                    continue
                if more:
                    if self._stopping:
                        with sub._lock:
                            sub._scheduled = False
                        continue
                    _POOL_RESCHEDULES.inc()
                    self.schedule(sub)

    # --- lifecycle ---

    def close(self, timeout: float = 5.0) -> None:
        """Stop accepting work and join the workers.  Queued subscribers
        still in flight finish their current drain round; their remaining
        events stay queued (the owning connections are torn down by the
        caller, same as per-thread subscribers on daemon shutdown)."""
        self._stopping = True
        for _ in self._threads:
            self._ready.put(None)
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=timeout)
