"""Scope-pushdown inverted index: script pubkey -> watching subscribers.

The single-fanout ``Broadcaster`` answers "who gets this diff?" by
scanning EVERY subscriber and intersecting its scope with the diff
(O(subscribers x min(scope, diff)) per event).  At 50k subscribers that
scan *is* the saturation wall the PR 16 load harness measured.  The
``ScopeIndex`` inverts the question: maintain script -> subscriber-set
entries on subscribe/unsubscribe/scope-mutation, so routing one diff
costs O(affected subscribers) — subscribers whose scopes miss the diff
are never touched (notify/src/address/tracker.rs role, inverted).

Wildcard subscribers (scope ``None``: "every address") live in a
separate always-hit set; they never inflate the per-script entries.

The index stores no payloads and makes no ordering promises of its own —
``route`` returns each affected subscriber's matched-script list in diff
order, and the caller sorts before building the payload, preserving the
single-fanout path's deterministic sorted-script payload byte-for-byte
(see ``serving/shards.py`` and the identity harness in
``serving/check.py``).

Thread safety: none here — every instance is owned by exactly one fanout
shard and mutated/read under that shard's ``serving.shard`` ranked lock.
"""

from __future__ import annotations


class ScopeIndex:
    """Inverted script->subscriber index for utxos-changed routing."""

    __slots__ = ("_watchers", "_wildcard")

    def __init__(self):
        # script pubkey (bytes) -> set of subscribers watching it
        self._watchers: dict = {}
        # subscribers with a wildcard scope: hit by every diff
        self._wildcard: set = set()

    # --- maintenance (subscribe / unsubscribe / scope mutation) ---

    def add(self, sub, scope) -> None:
        """Index ``sub`` under every script in ``scope`` (``None`` =
        wildcard)."""
        if scope is None:
            self._wildcard.add(sub)
            return
        watchers = self._watchers
        for s in scope:
            w = watchers.get(s)
            if w is None:
                watchers[s] = {sub}
            else:
                w.add(sub)

    def discard(self, sub, scope) -> None:
        """Drop ``sub``'s entries for ``scope`` (``None`` = wildcard).
        Unknown scripts / absent memberships are ignored."""
        if scope is None:
            self._wildcard.discard(sub)
            return
        watchers = self._watchers
        for s in scope:
            w = watchers.get(s)
            if w is not None:
                w.discard(sub)
                if not w:
                    del watchers[s]

    def update(self, sub, old, new) -> None:
        """Move ``sub`` from scope ``old`` to scope ``new`` touching only
        the delta — a million-address scope growing by one script costs
        one entry, not a re-index."""
        if old == new:
            return
        if old is None or new is None:
            self.discard(sub, old)
            self.add(sub, new)
            return
        self.add(sub, new - old)
        self.discard(sub, old - new)

    def clear(self) -> None:
        self._watchers.clear()
        self._wildcard.clear()

    # --- routing ---

    def route(self, scripts) -> dict:
        """Affected scoped subscribers for a diff touching ``scripts``
        (any iterable of script pubkeys, e.g. the per-event by_script
        index): {subscriber: [matched script, ...]}.  Matched lists
        follow ``scripts`` iteration order — callers sort before building
        payloads.  Wildcard subscribers are NOT included; read
        ``wildcard`` (always-hit) separately."""
        hits: dict = {}
        watchers = self._watchers
        for s in scripts:
            subs = watchers.get(s)
            if not subs:
                continue
            for sub in subs:
                lst = hits.get(sub)
                if lst is None:
                    hits[sub] = [s]
                else:
                    lst.append(s)
        return hits

    # --- introspection (tests / metrics) ---

    @property
    def wildcard(self) -> set:
        return self._wildcard

    def watchers(self, script):
        """Subscribers indexed under one script (empty tuple when none)."""
        return self._watchers.get(script, ())

    def script_count(self) -> int:
        return len(self._watchers)

    def entry_count(self) -> int:
        """Total (script, subscriber) pairs — the index's memory weight."""
        return sum(len(w) for w in self._watchers.values())
