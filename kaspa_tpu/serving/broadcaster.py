"""Backpressured notification fanout: the serving tier's broadcaster stage.

Reference: notify/src/broadcaster.rs + connection.rs — the reference hands
every notification to per-connection broadcaster tasks with bounded
channels, so one slow websocket can never stall the consensus thread (or
the other subscribers).  This module is that stage for all remote RPC
transports (line-JSON, wRPC JSON, wRPC Borsh):

  consensus root ──> rpc Notifier ──(wildcard listener)──> Broadcaster
                                                              │ ingest queue
                                                    broadcaster thread:
                                                    index diff by script ONCE,
                                                    filter per subscriber scope
                                                              │
                         ┌────────────────────────────────────┤
                   Subscriber A                          Subscriber B
                   bounded deque                         bounded deque
                   sender thread:                        sender thread:
                   encode + sink.put                     encode + sink.put

Scope filtering is pushed down: a UtxosChanged diff is indexed by script
once per event, then each subscriber's payload is built by iterating the
SMALLER of (its address set, the changed-script set) — a million-address
subscription costs O(|diff scripts|), never O(|addresses|) and never a
full-diff scan per subscriber (notify/src/address/tracker.rs role).

Backpressure policy at the bounded per-subscriber queue:
  * ``drop-oldest`` (default): overflow evicts the oldest queued event and
    counts it — the subscriber sees a gap, the node never blocks.
  * ``disconnect``: overflow tears the connection down (the reference's
    policy for pubsub channels that fall too far behind).
The sender thread blocks into the connection's outbound queue, so socket
backpressure propagates into the subscriber queue — where the policy, not
the publisher, absorbs it.
"""

from __future__ import annotations

import os
import queue
import sys
import threading

from kaspa_tpu.utils.sync import ranked_lock
from collections import deque
from contextlib import nullcontext
from time import monotonic, perf_counter_ns

from kaspa_tpu.core.log import get_logger
from kaspa_tpu.notify.notifier import EVENT_TYPES, Notification
from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import MS_LATENCY_BUCKETS, REGISTRY, SIZE_BUCKETS

log = get_logger("serving")

POLICY_DROP_OLDEST = "drop-oldest"
POLICY_DISCONNECT = "disconnect"
POLICIES = (POLICY_DROP_OLDEST, POLICY_DISCONNECT)

_INGEST_DROPS = REGISTRY.counter(
    "serving_ingest_dropped", help="notifications dropped at the broadcaster ingest queue (publisher never blocks)"
)
_FANOUT_EVENTS = REGISTRY.counter_family(
    "serving_fanout_events", "event", help="notifications fanned out by the broadcaster thread, per event type"
)
_SUB_DROPS = REGISTRY.counter(
    "serving_subscriber_dropped", help="events evicted from full subscriber queues (drop-oldest policy)"
)
_SUB_DISCONNECTS = REGISTRY.counter(
    "serving_subscriber_disconnects", help="subscribers torn down by the disconnect overflow policy"
)
_QUEUE_DEPTH = REGISTRY.histogram(
    "serving_subscriber_queue_depth", buckets=SIZE_BUCKETS,
    help="subscriber queue depth observed at each enqueue",
)
_LAG = REGISTRY.histogram_family(
    "serving_subscriber_lag_seconds", "encoding",
    help="broadcaster-receipt to connection-queue delivery lag, per wire encoding",
)
_FILTER_SCAN = REGISTRY.histogram(
    "serving_filter_scanned_scripts", buckets=SIZE_BUCKETS,
    help="scripts iterated to scope-filter one UtxosChanged event for one subscriber",
)

# --- the latency observatory: block-accept -> wire lag, per stage -------
#
# Every Notification carries its origin block's accept stamp
# (``t_accept_ns``, perf_counter_ns on the consensus thread).  The serving
# tier decomposes accept-to-socket lag into the stages below, in
# MILLISECONDS on the shared registry ladder (same edges as the flight
# recorder's critical-path families, so the two views line up bucket for
# bucket).  ``end_to_end`` is accept -> socket-write-complete; for a
# conflated event it is measured from the OLDEST merged diff's stamp.
LAG_STAGES = ("accept_to_fanout", "queue_wait", "encode", "socket_write", "end_to_end")
_LAG_MS = REGISTRY.histogram_family(
    "serving_lag_ms", "stage", MS_LATENCY_BUCKETS,
    help="block-accept to subscriber-socket-write notification lag decomposed by delivery stage (ms)",
)
_CONFLATE_MERGED = REGISTRY.histogram(
    "serving_conflation_merged_diffs", buckets=SIZE_BUCKETS,
    help="diffs folded into each delivered conflated utxos-changed notification",
)
# Sharded fanout tier (serving/shards.py): queue_wait decomposed per
# shard, so the overload plane can take the MAX across shards — one
# wedged shard must trip ELEVATED even while the other shards' fast
# deliveries would dilute a global mean to quiet.  Subscribers carrying a
# shard id observe into their shard's cell next to the global stage cell.
_SHARD_QUEUE_WAIT = REGISTRY.histogram_family(
    "serving_shard_queue_wait_ms", "shard", MS_LATENCY_BUCKETS,
    help="subscriber queue_wait lag per fanout shard (sharded serving tier; ms)",
)
# hot-path cells held once (the documented CounterFamily/HistogramFamily
# pattern): the delivery path runs per subscriber per event — at 50k
# subscribers a per-observe dict lookup is measurable against the 2%
# instrumentation-overhead budget
_LAG_ACCEPT_TO_FANOUT = _LAG_MS.cell("accept_to_fanout")
_LAG_QUEUE_WAIT = _LAG_MS.cell("queue_wait")
_LAG_ENCODE = _LAG_MS.cell("encode")
_LAG_SOCKET_WRITE = _LAG_MS.cell("socket_write")
_LAG_END_TO_END = _LAG_MS.cell("end_to_end")

# Tracing-off gate: with KASPA_TPU_SERVING_TRACE=0 the per-stage lag
# clock reads, histogram observes and retroactive queue-wait spans are
# all skipped — the payload byte stream is identical either way (stamps
# ride the Notification object, never the encoded data), and the
# roundcheck serving_load lane holds the off/on throughput ratio to the
# >=0.98x overhead gate.
_STAGE_TRACE = os.environ.get("KASPA_TPU_SERVING_TRACE", "1") != "0"


def register_serving_collector(collect) -> None:
    """The one registration site for the ``serving`` collector.  Both
    fanout tiers (Broadcaster and the sharded tier) publish their
    snapshot under this name; the registry merges numeric leaves across
    live instances, so whichever tier the daemon constructed reports."""
    REGISTRY.register_collector("serving", collect)


def unregister_serving_collector(collect) -> None:
    """close() symmetry for ``register_serving_collector``: a torn-down
    tier must stop contributing to the merged snapshot immediately, not
    whenever the garbage collector gets to it."""
    REGISTRY.unregister_collector("serving", collect)


def stage_tracing_enabled() -> bool:
    return _STAGE_TRACE


def set_stage_tracing(on: bool) -> None:
    """Flip per-stage serving lag instrumentation at runtime (the load
    harness A/Bs the overhead gate through this seam)."""
    global _STAGE_TRACE
    _STAGE_TRACE = bool(on)


def tune_gil_switch_interval() -> float:
    """Raise the interpreter's GIL switch interval for fanout-heavy
    processes and return the interval now in effect (seconds).

    The delivery path is pure-Python churn spread across many threads
    (shard routers, sender-pool crews, the wire selector); at the default
    5 ms quantum the interpreter forces a GIL handoff mid-burst thousands
    of times per second and the cache/convoy cost shows up directly as
    delivery throughput (~45% on the 50k-subscriber load harness on one
    core).  ``KASPA_TPU_GIL_SWITCH_MS`` (default 20, 0 disables) is
    raise-only: an operator who set a larger interval process-wide keeps
    it, and library code never *shrinks* the quantum behind the
    embedder's back."""
    try:
        ms = float(os.environ.get("KASPA_TPU_GIL_SWITCH_MS", "20") or 0.0)
    except ValueError:
        ms = 0.0
    if ms > 0 and ms * 1e-3 > sys.getswitchinterval():
        sys.setswitchinterval(ms * 1e-3)
    return sys.getswitchinterval()


from kaspa_tpu.observability.shed import SHED as _SHED  # noqa: E402  (family declared once there)


def _conflate_utxos_changed(old: Notification, new: Notification) -> Notification:
    """Merge two consecutive utxos-changed events into one (brownout
    diff-conflation for slow subscribers).  Added/removed lists concatenate
    in arrival order — replaying the merged diff yields the same final
    UTXO view a client would reach applying both — and the scope set is
    the union."""
    data = dict(new.data)
    data["added"] = list(old.data.get("added", ())) + list(new.data.get("added", ()))
    data["removed"] = list(old.data.get("removed", ())) + list(new.data.get("removed", ()))
    if old.data.get("spk_set") is not None or new.data.get("spk_set") is not None:
        data["spk_set"] = set(old.data.get("spk_set") or ()) | set(new.data.get("spk_set") or ())
    # lag honesty under brownout: the merged diff is only as fresh as its
    # OLDEST constituent — keep that accept stamp so conflation cannot
    # hide how stale a slow subscriber's view really is
    t_accept = min(old.t_accept_ns, new.t_accept_ns)
    return Notification(
        new.event_type, data, new.ctx,
        t_accept_ns=t_accept, merged=old.merged + new.merged + 1,
    )


class Subscriber:
    """One remote consumer: bounded queue + a sender (thread or pool).

    ``encoder(notification) -> bytes | None`` runs on the sender side
    (never on the broadcaster or consensus thread); ``None`` means the
    encoding cannot represent the event and it is skipped.  ``sink`` must
    expose ``put(item, timeout=...)`` raising ``queue.Full`` — the
    connection pump's outbound queue or a WebSocket frame adapter.

    With ``pool=None`` (default, the daemon's historical shape) each
    subscriber owns a dedicated sender thread.  With a ``SenderPool``
    (``kaspa_tpu.serving.pool``) the subscriber is a passive queue drained
    by the pool's shared workers — the shape the 50k-virtual-subscriber
    load harness needs, where one thread per consumer is not an option.
    """

    def __init__(
        self,
        name: str,
        encoder,
        sink,
        *,
        encoding: str = "json",
        maxlen: int = 1024,
        policy: str = POLICY_DROP_OLDEST,
        on_disconnect=None,
        pool=None,
        shard: int | None = None,
    ):
        if policy not in POLICIES:
            raise ValueError(f"unknown backpressure policy {policy!r}")
        self.name = name
        self.encoder = encoder
        self.sink = sink
        self.encoding = encoding
        self.maxlen = max(1, int(maxlen))
        self.policy = policy
        self.on_disconnect = on_disconnect
        # sharded fanout tier: which shard owns this subscriber (None =
        # the single-fanout path, byte-identical to the historical shape).
        # Sharded subscribers additionally keep an active-event set and an
        # in-flight marker (both under self._lock) so unsubscribe can
        # guarantee "no delivery of that event completes after unsubscribe
        # returns" — see retract().
        self.shard = shard
        self._shard_wait_cell = _SHARD_QUEUE_WAIT.cell(str(shard)) if shard is not None else None
        self._active_events: set | None = set() if shard is not None else None
        self._inflight_event: str | None = None
        self._retract_waiting = 0  # retract() callers parked on the cv
        # event type -> None (wildcard) | frozenset of script pubkeys.
        # Mutated copy-on-write under the owning Broadcaster's lock; the
        # broadcaster thread reads the frozen value without copying it.
        self.subscriptions: dict[str, frozenset | None] = {}
        self.dropped = 0
        self.delivered = 0
        self.conflated = 0
        # brownout knob: queue depth at/above which consecutive
        # utxos-changed events merge instead of appending (None = off)
        self.conflate_floor: int | None = None
        self._dq: deque = deque()  # graftlint: allow(unbounded-queue) -- bounded by the maxlen overflow policy in offer()
        self._lock = ranked_lock("serving.subscriber", reentrant=False)
        self._cv = self._lock.condition()
        self._stopped = False
        self._lag_cell = _LAG.cell(encoding)
        self._pool = pool
        # pool mode: True while this subscriber sits in (or is being
        # drained from) the pool's ready queue; guarded by self._lock so
        # a subscriber is scheduled at most once at any moment
        self._scheduled = False
        if pool is None:
            self._thread = threading.Thread(target=self._run, daemon=True, name=f"serving-{name}")
            self._thread.start()
        else:
            self._thread = None

    # --- broadcaster side ---

    def offer(
        self, notification: Notification, t_received_ns: int, defer_kick: bool = False
    ) -> bool:
        """Enqueue one event; applies the overflow policy, never blocks.

        ``t_received_ns`` is the broadcaster-receipt stamp
        (perf_counter_ns) — queue-wait lag is measured from it.

        ``defer_kick=True`` (sharded fanout workers): when a pool kick is
        due, return True instead of scheduling — the caller batches one
        ``schedule_many`` for the whole routed event rather than paying a
        ready-queue wakeup per subscriber.  Returns False otherwise.
        """
        disconnect = False
        kick = False
        with self._lock:
            if self._stopped:
                return False
            if (
                self._active_events is not None
                and notification.event_type not in self._active_events
            ):
                # sharded tier: a fanout worker routed from a membership
                # snapshot taken before an unsubscribe landed — the event
                # is no longer deliverable for this subscriber
                return False
            if len(self._dq) >= self.maxlen:
                if self.policy == POLICY_DISCONNECT:
                    disconnect = True
                else:
                    self._dq.popleft()
                    self.dropped += 1
                    _SUB_DROPS.inc()
            if not disconnect:
                floor = self.conflate_floor
                if (
                    floor is not None
                    and len(self._dq) >= max(1, floor)
                    and notification.event_type == "utxos-changed"
                    and self._dq
                    and self._dq[-1][0].event_type == "utxos-changed"
                ):
                    # brownout diff-conflation: a slow subscriber gets one
                    # merged diff (oldest receipt AND oldest accept stamp
                    # kept — lag telemetry still reflects how far behind
                    # the consumer is)
                    prev_n, prev_t = self._dq[-1]
                    self._dq[-1] = (_conflate_utxos_changed(prev_n, notification), prev_t)
                    self.conflated += 1
                    _SHED.inc("fanout_conflation")
                else:
                    self._dq.append((notification, t_received_ns))
                _QUEUE_DEPTH.observe(len(self._dq))
                if self._pool is None:
                    self._cv.notify()
                elif not self._scheduled:
                    self._scheduled = True
                    kick = True
        if kick:
            if defer_kick:
                return True
            self._pool.schedule(self)
        if disconnect:
            _SUB_DISCONNECTS.inc()
            log.info("subscriber %s overflowed (policy=disconnect): tearing down", self.name)
            self.stop()
            if self.on_disconnect is not None:
                try:
                    self.on_disconnect()
                except Exception:  # noqa: BLE001 - teardown callback must not kill fanout
                    log.exception("subscriber %s disconnect callback failed", self.name)
        return False

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._dq)

    # --- sharded-tier event membership (no-ops for shard=None) ---

    def activate(self, event: str) -> None:
        """Mark ``event`` deliverable.  The owning shard calls this under
        its shard lock in the same critical section that adds the index
        entry, so a routing snapshot either misses this subscriber or
        sees the event active — never a half-state."""
        with self._lock:
            if self._active_events is not None:
                self._active_events.add(event)

    def retract(self, event: str, timeout: float = 5.0) -> None:
        """Make "no delivery of ``event`` completes after this returns"
        true: drop the event from the active set (in-flight offers from a
        stale routing snapshot bounce), purge queued entries of the type,
        and wait out a delivery already mid-``_deliver``."""
        with self._lock:
            if self._active_events is not None:
                self._active_events.discard(event)
            if self._dq:
                kept = [it for it in self._dq if it[0].event_type != event]
                if len(kept) != len(self._dq):
                    self._dq.clear()
                    self._dq.extend(kept)
            deadline = monotonic() + timeout
            self._retract_waiting += 1
            try:
                while self._inflight_event == event and not self._stopped:
                    left = deadline - monotonic()
                    if left <= 0:
                        log.warning(
                            "subscriber %s: in-flight %s delivery outlived the "
                            "retract timeout", self.name, event,
                        )
                        break
                    self._cv.wait(timeout=left)
            finally:
                self._retract_waiting -= 1

    def _clear_inflight(self) -> None:
        # fast path (single-fanout subscribers): _inflight_event is never
        # set, so the plain delivery loop pays one attribute check
        if self._inflight_event is not None:
            with self._lock:
                self._inflight_event = None
                if self._retract_waiting:
                    self._cv.notify_all()

    # --- lifecycle ---

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            self._cv.notify_all()

    def close(self, timeout: float = 2.0) -> None:
        self.stop()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=timeout)

    # --- sender side (dedicated thread or pool worker) ---

    def _deliver(self, notification: Notification, t_received_ns: int) -> bool:
        """Encode + write one event to the sink, recording per-stage lag.
        Returns False only when the subscriber stopped mid-write."""
        staged = _STAGE_TRACE
        sinks_live = trace.sinks_active()
        ctx = getattr(notification, "ctx", None) if sinks_live else None
        t_dq = perf_counter_ns() if staged else 0
        if staged:
            wait_ms = (t_dq - t_received_ns) * 1e-6
            _LAG_QUEUE_WAIT.observe(wait_ms)
            if self._shard_wait_cell is not None:
                self._shard_wait_cell.observe(wait_ms)
            if sinks_live:
                # retroactive span: the interval this event sat in the
                # bounded subscriber queue, grafted onto the emitting
                # block's trace (flight ring / capture log only — when
                # neither collects, skip building a span nobody keeps).
                # Sharded subscribers tag the span with their shard so a
                # block's tree stays readable across shard threads.
                if self.shard is None:
                    ctx_wait = trace.record_span(
                        "wait.serving_queue", ctx, t_received_ns, t_dq, subscriber=self.name
                    )
                else:
                    ctx_wait = trace.record_span(
                        "wait.serving_queue", ctx, t_received_ns, t_dq,
                        subscriber=self.name, shard=self.shard,
                    )
                if ctx_wait is not None:
                    ctx = ctx_wait
        # delivery rides the emitting block's trace (cross-thread via
        # the Notification's captured context): encode + sink.put.
        # Span construction is gated on a live sink — at 10^5 deliveries
        # per event the per-span cost is the fanout tier's hot path
        if not sinks_live:
            deliver_span = nullcontext()
        elif self.shard is None:
            deliver_span = trace.span(
                "serving.deliver", parent=ctx,
                encoding=self.encoding, event=notification.event_type,
                merged=notification.merged,
            )
        else:
            deliver_span = trace.span(
                "serving.deliver", parent=ctx,
                encoding=self.encoding, event=notification.event_type,
                merged=notification.merged, shard=self.shard,
            )
        with deliver_span:
            try:
                payload = self.encoder(notification)
            except Exception:  # noqa: BLE001 - one bad encode must not kill the stream
                log.exception("subscriber %s: encoding %s failed", self.name, notification.event_type)
                return True
            t_enc = perf_counter_ns() if staged else 0
            if payload is None:
                return True
            # blocking put with a stop-aware retry loop: socket backpressure
            # (a full connection queue) parks THIS sender; the bounded deque
            # above is where the policy then absorbs the overflow
            while True:
                try:
                    self.sink.put(payload, timeout=0.25)
                    break
                except queue.Full:
                    with self._lock:
                        if self._stopped:
                            return False
        self.delivered += 1
        self._lag_cell.observe((perf_counter_ns() - t_received_ns) * 1e-9)
        if staged:
            t_done = perf_counter_ns()
            _LAG_ENCODE.observe((t_enc - t_dq) * 1e-6)
            _LAG_SOCKET_WRITE.observe((t_done - t_enc) * 1e-6)
            _LAG_END_TO_END.observe((t_done - notification.t_accept_ns) * 1e-6)
            if notification.merged:
                _CONFLATE_MERGED.observe(notification.merged + 1)
        return True

    def _run(self) -> None:
        while True:
            with self._lock:
                while not self._dq and not self._stopped:
                    self._cv.wait(timeout=0.5)
                if self._dq:
                    notification, t_received_ns = self._dq.popleft()
                    if self._active_events is not None:
                        self._inflight_event = notification.event_type
                elif self._stopped:
                    return
                else:
                    continue
            try:
                ok = self._deliver(notification, t_received_ns)
            finally:
                # even on an unexpected sink/encoder escape: a retract()
                # waiting on this event must not stall to its timeout
                self._clear_inflight()
            if not ok:
                return

    def _pool_drain(self, batch: int) -> bool:
        """Pool-worker seam: deliver up to ``batch`` queued events.
        Returns True when events remain (the worker must reschedule this
        subscriber), False when the queue drained or the subscriber
        stopped — in both False cases ``_scheduled`` has been cleared
        under the lock, so the next ``offer`` re-kicks the pool.

        The sharded in-flight marker is cleared inside the NEXT
        iteration's lock acquisition (one round trip per delivery, not
        two); the ``finally`` covers the exits where no next acquisition
        happens, so a parked retract() never waits out its timeout."""
        cleared = True
        try:
            for _ in range(max(1, batch)):
                with self._lock:
                    if not cleared:
                        self._inflight_event = None
                        cleared = True
                        if self._retract_waiting:
                            self._cv.notify_all()
                    if self._stopped or not self._dq:
                        self._scheduled = False
                        return False
                    notification, t_received_ns = self._dq.popleft()
                    if self._active_events is not None:
                        self._inflight_event = notification.event_type
                        cleared = False
                ok = self._deliver(notification, t_received_ns)
                if not ok:
                    with self._lock:
                        self._scheduled = False
                    return False
            return True
        finally:
            if not cleared:
                self._clear_inflight()


class Broadcaster:
    """Async fanout stage between one Notifier and many Subscribers.

    Holds a single wildcard listener on the RPC notifier (per active event
    type, refcounted across subscribers) — the notifier object survives
    consensus staging swaps via ``rebind_parent``, so the listener id stays
    valid for the daemon's lifetime.  ``publish`` (the notifier callback)
    only enqueues; indexing, filtering and delivery run on the broadcaster
    thread.

    Thread safety: ``subscribe``/``unsubscribe``/``register``/``unregister``
    must be called under the daemon dispatch lock (they mutate the shared
    Notifier exactly like the old direct-listener path did); ``publish``
    is called by the notifier with that lock already held and never blocks.
    """

    def __init__(self, notifier, ingest_maxsize: int = 8192):
        self.notifier = notifier
        self._ingest: queue.Queue = queue.Queue(maxsize=ingest_maxsize)
        self._mu = ranked_lock("serving.broadcaster", reentrant=False)
        self._conflate_floor: int | None = None
        self._subscribers: list[Subscriber] = []
        self._event_refs: dict[str, int] = {}
        self._closed = False
        # fanout-thread utilization: ns spent processing events (vs idle
        # blocked on the ingest queue) and events handled — written only
        # by the broadcaster thread, read by the saturation probe
        self.fanout_busy_ns = 0
        self.fanout_events = 0
        self._lid = notifier.register(self.publish)
        self._thread = threading.Thread(target=self._run, daemon=True, name="serving-broadcaster")
        self._thread.start()
        register_serving_collector(self._collect)

    # --- observability ---

    def _collect(self) -> dict:
        """The ``serving`` block of the observability snapshot (getMetrics
        + Prometheus gauges): fanout state plus per-stage lag quantiles."""
        with self._mu:
            subs = list(self._subscribers)
        out = {
            "subscribers": len(subs),
            "ingest_depth": self._ingest.qsize(),
            "max_queue_depth": max((s.queue_depth() for s in subs), default=0),
            "dropped": sum(s.dropped for s in subs),
            "delivered": sum(s.delivered for s in subs),
            "conflated": sum(s.conflated for s in subs),
            "stage_tracing": int(_STAGE_TRACE),
            "fanout": {"events": self.fanout_events, "busy_ns": self.fanout_busy_ns},
            # key must NOT be "lag_ms": the gauge tree flattens to
            # kaspa_serving_<key>_p50 in the Prometheus export, and a
            # _p50 sample under the TYPEd kaspa_serving_lag_ms histogram
            # family name is an exposition-format violation
            "lag_quantiles_ms": {
                stage: {
                    "count": h.count,
                    "p50": h.quantile(0.50),
                    "p99": h.quantile(0.99),
                    "p999": h.quantile(0.999),
                }
                for stage, h in sorted(_LAG_MS._cells.items())
                if h.count
            },
        }
        if len(subs) <= 64:
            # per-subscriber detail only at interactive population sizes —
            # a 50k-subscriber load run must not turn every metrics scrape
            # into a 50k-entry gauge dump
            out["queue_depths"] = {s.name: s.queue_depth() for s in subs}
            out["dropped_by_subscriber"] = {s.name: s.dropped for s in subs if s.dropped}
        return out

    def max_queue_depth(self) -> int:
        """Deepest per-subscriber queue (the overload fanout signal)."""
        with self._mu:
            subs = list(self._subscribers)
        return max((s.queue_depth() for s in subs), default=0)

    def pending(self) -> int:
        """Events queued at the fanout ingest (shared drain seam with the
        sharded tier — load harnesses poll this instead of reaching into
        the queue object)."""
        return self._ingest.qsize()

    def set_conflation(self, floor: int | None) -> None:
        """Brownout seam: enable utxos-changed diff-conflation for every
        subscriber whose queue depth reaches ``floor`` (None disables)."""
        with self._mu:
            self._conflate_floor = floor
            subs = list(self._subscribers)
        for s in subs:
            s.conflate_floor = floor

    # --- subscriber lifecycle (call under the daemon dispatch lock) ---

    def register(self, sub: Subscriber) -> Subscriber:
        with self._mu:
            self._subscribers.append(sub)
            sub.conflate_floor = self._conflate_floor
        return sub

    def unregister(self, sub: Subscriber) -> None:
        """Detach a subscriber and release its upstream event refs.  The
        caller closes the subscriber (joins its thread) outside any lock."""
        with self._mu:
            if sub not in self._subscribers:
                return
            self._subscribers.remove(sub)
            events = list(sub.subscriptions)
            sub.subscriptions = {}
        for event in events:
            self._release_event(event)
        sub.stop()

    def subscribe(self, sub: Subscriber, event: str, scripts: set | None = None) -> None:
        """Activate ``event`` for a subscriber.  ``scripts`` is the UtxosChanged
        address scope (script pubkeys); ``None``/empty means wildcard.
        Repeated subscribes OR scopes together; a wildcard subscribe makes
        the scope wildcard and stays so until unsubscribe."""
        if event not in EVENT_TYPES:
            raise ValueError(f"unknown event type {event!r}")
        first = False
        with self._mu:
            known = event in sub.subscriptions
            prev = sub.subscriptions.get(event)
            if not known:
                self._event_refs[event] = self._event_refs.get(event, 0) + 1
                first = self._event_refs[event] == 1
            if not scripts:
                sub.subscriptions[event] = None  # wildcard (and sticky)
            elif known and prev is None:
                pass  # already wildcard: narrowing via subscribe is not a thing
            else:
                base = prev if prev is not None else frozenset()
                sub.subscriptions[event] = base | frozenset(scripts)
        if first:
            # upstream subscription is wildcard: the broadcaster needs the
            # full diff to index it once and filter per subscriber
            self.notifier.start_notify(self._lid, event)

    def unsubscribe(self, sub: Subscriber, event: str) -> None:
        if event not in EVENT_TYPES:
            raise ValueError(f"unknown event type {event!r}")
        with self._mu:
            if event not in sub.subscriptions:
                return
            del sub.subscriptions[event]
        self._release_event(event)

    def _release_event(self, event: str) -> None:
        with self._mu:
            n = self._event_refs.get(event, 0) - 1
            if n > 0:
                self._event_refs[event] = n
                return
            self._event_refs.pop(event, None)
            if self._closed:
                return
        self.notifier.stop_notify(self._lid, event)

    # --- publisher side (notifier callback; must never block) ---

    def publish(self, notification: Notification) -> None:
        try:
            self._ingest.put_nowait(notification)
        except queue.Full:
            _INGEST_DROPS.inc()

    # --- broadcaster thread ---

    @staticmethod
    def _index_diff(n: Notification) -> dict:
        """script pubkey -> (added pairs, removed pairs), built once per event."""
        by_script: dict = {}
        for slot, key in ((0, "added"), (1, "removed")):
            for pair in n.data.get(key, ()):
                s = pair[1].script_public_key.script
                bucket = by_script.get(s)
                if bucket is None:
                    bucket = by_script[s] = ([], [])
                bucket[slot].append(pair)
        return by_script

    @staticmethod
    def _filter_utxos_changed(n: Notification, scope: frozenset, by_script: dict) -> Notification | None:
        # iterate the smaller side of the scope/diff intersection
        if len(scope) <= len(by_script):
            matched = [s for s in scope if s in by_script]
        else:
            matched = [s for s in by_script if s in scope]
        _FILTER_SCAN.observe(min(len(scope), len(by_script)))
        if not matched:
            return None
        # sorted script order: deterministic payloads, so two subscribers
        # with the same scope see byte-identical streams on any encoding
        matched.sort()
        added: list = []
        removed: list = []
        for s in matched:
            a, r = by_script[s]
            added.extend(a)
            removed.extend(r)
        data = dict(n.data)
        data["added"] = added
        data["removed"] = removed
        data["spk_set"] = set(matched)
        return Notification(n.event_type, data, n.ctx, t_accept_ns=n.t_accept_ns, merged=n.merged)

    def _run(self) -> None:
        while True:
            n = self._ingest.get()
            if n is None:
                return
            t0_ns = perf_counter_ns()
            _FANOUT_EVENTS.inc(n.event_type)
            if _STAGE_TRACE and n.t_accept_ns:
                # consensus-side half of the lag budget: block accept ->
                # fanout-thread pickup (includes the ingest queue wait)
                _LAG_ACCEPT_TO_FANOUT.observe((t0_ns - n.t_accept_ns) * 1e-6)
            with trace.span(
                "serving.fanout", parent=getattr(n, "ctx", None), event=n.event_type,
            ):
                by_script = self._index_diff(n) if n.event_type == "utxos-changed" else None
                with self._mu:
                    targets = [
                        (sub, sub.subscriptions[n.event_type])
                        for sub in self._subscribers
                        if n.event_type in sub.subscriptions
                    ]
                for sub, scope in targets:
                    if by_script is not None and scope is not None:
                        filtered = self._filter_utxos_changed(n, scope, by_script)
                        if filtered is None:
                            continue
                        sub.offer(filtered, t0_ns)
                    else:
                        sub.offer(n, t0_ns)
            self.fanout_events += 1
            self.fanout_busy_ns += perf_counter_ns() - t0_ns

    # --- lifecycle ---

    def close(self) -> None:
        """Stop the fanout: detach from the notifier, stop the broadcaster
        thread, stop every subscriber.  Call under the daemon dispatch lock
        (notifier mutation), like subscribe/unsubscribe."""
        with self._mu:
            if self._closed:
                return
            self._closed = True
            subs = list(self._subscribers)
            self._subscribers.clear()
            self._event_refs.clear()
        self.notifier.unregister(self._lid)
        self._ingest.put(None)
        self._thread.join(timeout=5.0)
        for sub in subs:
            sub.close()
        unregister_serving_collector(self._collect)
