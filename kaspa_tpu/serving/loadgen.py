"""Virtual-subscriber load generation for the serving latency observatory.

Drives a real ``Broadcaster`` (the production fanout path: ingest queue,
per-event script indexing, scope filtering, bounded subscriber queues,
sender pool) with a deterministic synthetic population:

* **Virtual subscribers** are real ``Subscriber`` objects in pool mode —
  no thread per consumer.  Most terminate in a ``MemorySink`` (zero fds);
  a configurable *wire cohort* terminates in a datagram socketpair whose
  far ends are drained by ONE selector-driven reader thread, so socket
  write pressure and kernel buffer behavior are exercised without a
  thread or fd explosion (2 fds per wire subscriber, preflighted by
  ``kaspa_tpu.utils.fdbudget``).
* **Address scopes are zipf-distributed**: subscriber k watches a few
  addresses sampled from a power-law popularity ranking, so hot addresses
  accumulate thousands of watchers exactly like a real exchange wallet.
* **The diff driver** publishes paced utxos-changed notifications whose
  addresses are mostly uniform (background payments) with a configurable
  hot fraction sampled by popularity (bursts that fan out wide).

Every delivered notification carries its origin accept stamp in the
payload, so lag is measured at the LAST hop (sink/datagram receipt) on
the same monotonic clock that stamped it — independent of (and therefore
able to cross-check) the ``serving_lag_ms`` histograms the broadcaster
records internally.
"""

from __future__ import annotations

import queue
import random
import selectors
import socket
import struct
import threading
import time
from bisect import bisect_left
from time import perf_counter_ns

from kaspa_tpu.core.log import get_logger
from kaspa_tpu.notify.notifier import Notification, Notifier
from kaspa_tpu.serving.broadcaster import Broadcaster, Subscriber
from kaspa_tpu.serving.pool import SenderPool
from kaspa_tpu.serving.shards import ShardedBroadcaster

log = get_logger("serving")

_FRAME = struct.Struct("<qii")  # accept stamp ns, merged count, added count


# --------------------------------------------------------------------------
# synthetic address universe
# --------------------------------------------------------------------------


class _Spk:
    __slots__ = ("script",)

    def __init__(self, script: bytes):
        self.script = script


class _Entry:
    """Minimal stand-in for a UTXO entry: exactly the attribute surface
    ``Broadcaster._index_diff`` and scope filtering touch."""

    __slots__ = ("script_public_key", "amount")

    def __init__(self, script: bytes, amount: int):
        self.script_public_key = _Spk(script)
        self.amount = amount


class AddressUniverse:
    """Deterministic address set with zipf(s) popularity ranking."""

    def __init__(self, count: int = 50_000, s: float = 1.05, seed: int = 0):
        self.count = int(count)
        self.scripts = [b"spk-%08d" % i for i in range(self.count)]
        self.entries = [_Entry(spk, 100_000_000 + i) for i, spk in enumerate(self.scripts)]
        # cumulative zipf weights for O(log n) popularity sampling
        total = 0.0
        cum = []
        for rank in range(1, self.count + 1):
            total += 1.0 / (rank**s)
            cum.append(total)
        self._cum = cum
        self.seed = seed

    def sample_hot(self, rnd: random.Random, k: int) -> list[int]:
        """k address indices by popularity (zipf weights, with repeats)."""
        cum, top = self._cum, self._cum[-1]
        return [
            min(self.count - 1, bisect_left(cum, rnd.random() * top)) for _ in range(k)
        ]

    def sample_uniform(self, rnd: random.Random, k: int) -> list[int]:
        return [rnd.randrange(self.count) for _ in range(k)]


# --------------------------------------------------------------------------
# lag recording + sinks
# --------------------------------------------------------------------------


class LagRecorder:
    """Bounded lag-sample store shared by every sink: exact quantiles over
    up to ``cap`` samples (oldest overwritten ring-style past the cap) and
    a total observation count.  list.append / index assignment are
    GIL-atomic, so sinks on pool workers record lock-free."""

    def __init__(self, cap: int = 200_000):
        self.cap = int(cap)
        self.samples: list[float] = []
        self.count = 0

    def record(self, lag_ms: float) -> None:
        if len(self.samples) < self.cap:
            self.samples.append(lag_ms)
        else:
            self.samples[self.count % self.cap] = lag_ms
        self.count += 1

    def reset(self) -> None:
        self.samples = []
        self.count = 0

    QS = (("p50", 0.50), ("p99", 0.99), ("p999", 0.999))

    def percentiles(self) -> dict:
        if not self.samples:
            return {"count": self.count, **{name: 0.0 for name, _ in self.QS}}
        ordered = sorted(self.samples)
        out: dict = {"count": self.count, "max": ordered[-1]}
        for name, q in self.QS:
            out[name] = ordered[min(len(ordered) - 1, int(q * len(ordered)))]
        return out


class MemorySink:
    """Zero-fd sink: unpacks the accept stamp and records last-hop lag."""

    __slots__ = ("rec",)

    def __init__(self, rec: LagRecorder):
        self.rec = rec

    def put(self, payload: bytes, timeout=None) -> None:
        t_accept, _merged, _adds = _FRAME.unpack_from(payload)
        self.rec.record((perf_counter_ns() - t_accept) * 1e-6)


class WireSink:
    """Datagram-socketpair sink: the sender side of a wire-cohort
    subscriber.  SOCK_DGRAM keeps message boundaries, so the reader needs
    no stream reassembly and a kernel-buffer overflow surfaces here as
    ``queue.Full`` — engaging the subscriber's real overflow policy."""

    __slots__ = ("sock",)

    def __init__(self, sock: socket.socket):
        self.sock = sock

    def put(self, payload: bytes, timeout=None) -> None:
        self.sock.settimeout(timeout)
        try:
            self.sock.send(payload)
        except (socket.timeout, BlockingIOError, OSError) as e:
            raise queue.Full from e


class WireReader:
    """One selector thread draining every wire-cohort receive socket."""

    def __init__(self, rec: LagRecorder):
        self.rec = rec
        self.received = 0
        self._sel = selectors.DefaultSelector()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True, name="loadgen-wire-reader")
        self._started = False

    def add(self, sock: socket.socket) -> None:
        sock.setblocking(False)
        self._sel.register(sock, selectors.EVENT_READ)
        if not self._started:
            self._started = True
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            for key, _ in self._sel.select(timeout=0.1):
                sock = key.fileobj
                while True:
                    try:
                        payload = sock.recv(65536)
                    except (BlockingIOError, InterruptedError):
                        break
                    except OSError:
                        break
                    if not payload:
                        break
                    t_accept, _merged, _adds = _FRAME.unpack_from(payload)
                    self.rec.record((perf_counter_ns() - t_accept) * 1e-6)
                    self.received += 1

    def close(self) -> None:
        self._stop.set()
        if self._started:
            self._thread.join(timeout=2.0)
        for key in list(self._sel.get_map().values()):
            try:
                key.fileobj.close()
            except OSError:
                pass
        self._sel.close()


def _encode(n: Notification) -> bytes:
    """The virtual wire encoding: accept stamp + merge count + diff size.
    A fixed-size frame keeps encode cost flat so stage timings measure the
    serving plane, not a JSON library."""
    return _FRAME.pack(n.t_accept_ns, n.merged, len(n.data.get("added", ())))


# --------------------------------------------------------------------------
# the population
# --------------------------------------------------------------------------


class LoadGen:
    """A broadcaster + sender pool + ramped virtual-subscriber population.

    Deterministic for a fixed seed: scope assignment, diff addresses and
    pacing order all come from one ``random.Random``.
    """

    def __init__(
        self,
        *,
        seed: int = 7,
        addresses: int = 50_000,
        zipf_s: float = 1.05,
        scope_min: int = 1,
        scope_max: int = 8,
        sub_maxlen: int = 1024,
        pool_workers: int = 2,
        pool_batch: int = 64,
        ingest_maxsize: int = 8192,
        recorder_cap: int = 200_000,
        shards: int = 0,
    ):
        self.rnd = random.Random(seed)
        self.universe = AddressUniverse(addresses, zipf_s, seed)
        self.scope_min = max(1, int(scope_min))
        self.scope_max = max(self.scope_min, int(scope_max))
        self.sub_maxlen = int(sub_maxlen)
        self.notifier = Notifier("loadgen-root")
        self.shards = int(shards)
        if self.shards > 1:
            # sharded tier: the pool budget splits across per-shard crews
            # (each shard owns its senders), no shared pool
            per_shard = max(1, -(-pool_workers // self.shards)) if pool_workers > 0 else 0
            self.pool = None
            self.broadcaster = ShardedBroadcaster(
                self.notifier, shards=self.shards, ingest_maxsize=ingest_maxsize,
                pool_workers=per_shard, pool_batch=pool_batch,
            )
        else:
            self.pool = SenderPool(workers=pool_workers, batch=pool_batch)
            self.broadcaster = Broadcaster(self.notifier, ingest_maxsize=ingest_maxsize)
        self.recorder = LagRecorder(cap=recorder_cap)
        self.wire_reader: WireReader | None = None
        self.subscribers: list[Subscriber] = []
        self.disconnects = 0
        self.events_published = 0
        self._seq = 0

    # --- population ramp ---

    def ramp_to(self, n: int, wire: int = 0) -> None:
        """Grow the population to ``n`` subscribers, the first ``wire`` of
        the NEW ones terminating in datagram socketpairs."""
        n = int(n)
        wire_left = int(wire)
        while len(self.subscribers) < n:
            i = len(self.subscribers)
            k = self.rnd.randint(self.scope_min, self.scope_max)
            scope = {self.universe.scripts[j] for j in self.universe.sample_hot(self.rnd, k)}
            if wire_left > 0:
                wire_left -= 1
                send_sock, recv_sock = socket.socketpair(socket.AF_UNIX, socket.SOCK_DGRAM)
                if self.wire_reader is None:
                    self.wire_reader = WireReader(self.recorder)
                self.wire_reader.add(recv_sock)
                sink = WireSink(send_sock)
            else:
                sink = MemorySink(self.recorder)
            name = f"vsub-{i:06d}"
            if self.shards > 1:
                pool = self.broadcaster.sender_pool_for(name)
                shard = self.broadcaster.shard_of(name)
            else:
                pool, shard = self.pool, None
            sub = Subscriber(
                name, _encode, sink,
                encoding="loadgen", maxlen=self.sub_maxlen, pool=pool,
                on_disconnect=self._on_disconnect, shard=shard,
            )
            self.broadcaster.register(sub)
            self.broadcaster.subscribe(sub, "utxos-changed", scope)
            self.subscribers.append(sub)

    def _on_disconnect(self) -> None:
        self.disconnects += 1

    # --- diff driver ---

    def publish_diff(self, size: int = 24, hot_frac: float = 0.125) -> None:
        """One synthetic utxos-changed diff: ``size`` touched addresses,
        ``hot_frac`` of them popularity-sampled (wide fanout), the rest
        uniform (background payments).  The Notification stamps its own
        accept time at construction — the same seam consensus uses."""
        hot = max(0, min(size, int(round(size * hot_frac))))
        idxs = self.universe.sample_hot(self.rnd, hot) + self.universe.sample_uniform(
            self.rnd, size - hot
        )
        added = []
        spk_set = set()
        for j in idxs:
            e = self.universe.entries[j]
            added.append((self._seq, e))
            spk_set.add(e.script_public_key.script)
            self._seq += 1
        self.broadcaster.publish(
            Notification(
                "utxos-changed",
                {"added": added, "removed": [], "spk_set": spk_set},
            )
        )
        self.events_published += 1

    def drive(self, events: int, pace_hz: float = 0.0, size: int = 24, hot_frac: float = 0.125) -> float:
        """Publish ``events`` diffs, paced at ``pace_hz`` (0 = unpaced
        back-to-back).  Returns the wall seconds spent publishing."""
        period = (1.0 / pace_hz) if pace_hz > 0 else 0.0
        t0 = time.monotonic()
        deadline = t0
        for _ in range(int(events)):
            self.publish_diff(size=size, hot_frac=hot_frac)
            if period:
                deadline += period
                delay = deadline - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
        return time.monotonic() - t0

    # --- settling + stats ---

    def drain(self, timeout: float = 60.0, settle: float = 0.05) -> bool:
        """Wait until the ingest queue, every subscriber queue and the
        sender pool go idle and the lag-sample count stops moving."""
        deadline = time.monotonic() + timeout
        last_count = -1
        while time.monotonic() < deadline:
            busy = (
                self.broadcaster.pending() > 0
                or self._senders_pending() > 0
                # lock-free depth probe: len(deque) is GIL-atomic, and at
                # 50k subscribers a locked queue_depth() sweep costs ~0.1 s
                # of the single core per poll — the measuring loop would
                # starve the delivery threads it is waiting on
                or any(s._dq for s in self.subscribers)
            )
            count = self.recorder.count
            if not busy and count == last_count:
                return True
            last_count = count
            time.sleep(settle)
        return False

    def _senders_pending(self) -> int:
        if self.pool is not None:
            return self.pool.pending()
        return self.broadcaster.senders_pending()

    def dropped(self) -> int:
        return sum(s.dropped for s in self.subscribers)

    def conflated(self) -> int:
        return sum(s.conflated for s in self.subscribers)

    def delivered(self) -> int:
        return sum(s.delivered for s in self.subscribers)

    def fanout_busy_ns(self) -> int:
        return self.broadcaster.fanout_busy_ns

    def reset_window(self) -> dict:
        """Snapshot-and-reset the measurement window (between ramp stages):
        returns the marker the next window's deltas are computed against."""
        marker = {
            "busy_ns": self.broadcaster.fanout_busy_ns,
            "events": self.broadcaster.fanout_events,
            "dropped": self.dropped(),
            "conflated": self.conflated(),
            "delivered": self.delivered(),
            "disconnects": self.disconnects,
        }
        self.recorder.reset()
        return marker

    def close(self) -> None:
        self.broadcaster.close()
        if self.pool is not None:
            self.pool.close()
        if self.wire_reader is not None:
            self.wire_reader.close()
        for s in self.subscribers:
            sink = s.sink
            if isinstance(sink, WireSink):
                try:
                    sink.sock.close()
                except OSError:
                    pass
