"""Sharded-vs-single fanout identity check (roundcheck ``serving_load`` section).

Builds one deterministic subscriber population (zipf-ish scopes over a
small address universe, a wildcard cohort, a block-added cohort) and one
recorded diff sequence (24 "blocks" of utxos-changed diffs with explicit
accept stamps, plus block-added beats), then replays the SAME sequence
through:

- **single**: the PR 6 ``Broadcaster`` (one fanout thread, per-subscriber
  scope filtering);
- **sharded**: ``ShardedBroadcaster`` with N shards (splitter + scope
  index + partitioned workers).

Mid-sequence (at drained barriers, so ordering stays comparable) the
population churns exactly the way a live node's would: scopes grow,
subscribers unsubscribe, unregister and join — the index-maintenance
paths, not just steady-state routing.

Gate: per-subscriber delivered byte streams are **bit-identical** between
the two runs (the canonical encoder serializes every payload field the
wire encodings can see: diff pairs in order, scope set, accept stamp,
merge count).  Emits one JSON line; exit 0 iff ``serving_identity_ok``.

    python -m kaspa_tpu.serving.check --shards 4 --blocks 24
"""

from __future__ import annotations

import argparse
import json
import random
import time

from kaspa_tpu.notify.notifier import Notification, Notifier
from kaspa_tpu.serving.broadcaster import Broadcaster, Subscriber
from kaspa_tpu.serving.loadgen import AddressUniverse
from kaspa_tpu.serving.shards import ShardedBroadcaster


def _canon_encode(n: Notification) -> bytes:
    """Canonical byte serialization of everything a wire encoding could
    render: any routing/payload divergence between the two fanout tiers
    becomes a byte difference."""
    return repr(
        (
            n.event_type,
            [(k, e.script_public_key.script, e.amount) for k, e in n.data.get("added", ())],
            [(k, e.script_public_key.script, e.amount) for k, e in n.data.get("removed", ())],
            sorted(n.data.get("spk_set") or ()),
            n.t_accept_ns,
            n.merged,
        )
    ).encode()


class _CaptureSink:
    __slots__ = ("items",)

    def __init__(self):
        self.items: list[bytes] = []

    def put(self, payload: bytes, timeout=None) -> None:
        self.items.append(payload)


def _scope_plan(universe: AddressUniverse, subs: int, seed: int) -> list:
    """[(name, scope-or-None, also_blocks)] — deterministic population."""
    rnd = random.Random(seed)
    plan = []
    for i in range(subs):
        name = f"csub-{i:04d}"
        if i % 17 == 0:
            scope = None  # wildcard cohort
        else:
            k = rnd.randint(1, 6)
            scope = {universe.scripts[j] for j in universe.sample_hot(rnd, k)}
        plan.append((name, scope, i % 11 == 0))
    return plan


def _diff_plan(universe: AddressUniverse, blocks: int, seed: int) -> list:
    """Recorded diff sequence: per block, one utxos-changed diff (mixed
    hot/uniform addresses, a few removed pairs) and — every 3rd block — a
    block-added beat.  Accept stamps are explicit (block ordinal), so the
    two replays produce identical bytes regardless of wall clock."""
    rnd = random.Random(seed ^ 0x5EED)
    seq = 0
    out = []
    for b in range(blocks):
        idxs = universe.sample_hot(rnd, 4) + universe.sample_uniform(rnd, 12)
        added, removed, spk_set = [], [], set()
        for j in idxs:
            e = universe.entries[j]
            added.append((seq, e))
            spk_set.add(e.script_public_key.script)
            seq += 1
        for j in universe.sample_uniform(rnd, 3):
            e = universe.entries[j]
            removed.append((seq, e))
            spk_set.add(e.script_public_key.script)
            seq += 1
        out.append(
            Notification(
                "utxos-changed",
                {"added": added, "removed": removed, "spk_set": spk_set},
                None,
                t_accept_ns=b + 1,
            )
        )
        if b % 3 == 0:
            out.append(
                Notification("block-added", {"block": f"blk-{b:04d}"}, None, t_accept_ns=b + 1)
            )
    return out


def _drain(bc, subs: list, timeout: float = 30.0) -> bool:
    """Barrier: fanout queues empty, subscriber queues empty, delivered
    counts stable across two polls."""
    deadline = time.monotonic() + timeout
    last = -1
    while time.monotonic() < deadline:
        busy = bc.pending() > 0 or any(s.queue_depth() for s in subs)
        total = sum(s.delivered for s in subs)
        if not busy and total == last:
            return True
        last = total
        time.sleep(0.01)
    return False


def _replay(make_bc, universe: AddressUniverse, plan: list, diffs: list, seed: int) -> dict:
    """Run the recorded sequence through one fanout tier; returns
    {subscriber name: [delivered payload bytes, ...]} plus drain flags."""
    notifier = Notifier("serving-check")
    bc = make_bc(notifier)
    sinks: dict[str, _CaptureSink] = {}
    by_name: dict[str, Subscriber] = {}
    for name, scope, also_blocks in plan:
        sink = _CaptureSink()
        sinks[name] = sink
        sub = Subscriber(name, _canon_encode, sink, encoding="check", maxlen=4096)
        by_name[name] = sub
        bc.register(sub)
        bc.subscribe(sub, "utxos-changed", scope)
        if also_blocks:
            bc.subscribe(sub, "block-added")

    rnd = random.Random(seed ^ 0xC0FFEE)
    drains_ok = True
    third = max(1, len(diffs) // 3)
    live = [name for name, _, _ in plan]

    def barrier() -> None:
        nonlocal drains_ok
        drains_ok = _drain(bc, [by_name[n] for n in live]) and drains_ok

    for i, n in enumerate(diffs):
        notifier.notify(n)
        if i == third:
            # churn wave 1: scopes grow (delta index maintenance), a few
            # subscribers unsubscribe utxos-changed
            barrier()
            for name in live[3:30:7]:
                grow = {universe.scripts[j] for j in universe.sample_hot(rnd, 2)}
                bc.subscribe(by_name[name], "utxos-changed", grow)
            for name in live[5:40:9]:
                if "utxos-changed" in by_name[name].subscriptions:
                    bc.unsubscribe(by_name[name], "utxos-changed")
        elif i == 2 * third:
            # churn wave 2: unregisters + late joiners
            barrier()
            for name in list(live[2:36:11]):
                bc.unregister(by_name[name])
                by_name[name].close()
                live.remove(name)
            for j in range(4):
                name = f"csub-late{j}"
                sink = _CaptureSink()
                sinks[name] = sink
                sub = Subscriber(name, _canon_encode, sink, encoding="check", maxlen=4096)
                by_name[name] = sub
                bc.register(sub)
                scope = {universe.scripts[x] for x in universe.sample_hot(rnd, 3)}
                bc.subscribe(sub, "utxos-changed", scope)
                live.append(name)
    barrier()
    bc.close()
    return {
        "streams": {name: list(sink.items) for name, sink in sorted(sinks.items())},
        "drained": drains_ok,
    }


def run_check(shards: int = 4, blocks: int = 24, subs: int = 120, seed: int = 11) -> dict:
    universe = AddressUniverse(400, 1.05, seed)
    plan = _scope_plan(universe, subs, seed)
    single = _replay(
        lambda notifier: Broadcaster(notifier),
        universe, plan, _diff_plan(universe, blocks, seed), seed,
    )
    sharded = _replay(
        lambda notifier: ShardedBroadcaster(notifier, shards=shards),
        universe, plan, _diff_plan(universe, blocks, seed), seed,
    )
    a, b = single["streams"], sharded["streams"]
    mismatched = sorted(
        name for name in set(a) | set(b) if a.get(name) != b.get(name)
    )
    identical = not mismatched
    deliveries = sum(len(v) for v in a.values())
    return {
        "shards": shards,
        "blocks": blocks,
        "subscribers": subs,
        "deliveries_single": deliveries,
        "deliveries_sharded": sum(len(v) for v in b.values()),
        "streams_identical": identical,
        "mismatched": mismatched[:8],
        "drained_single": single["drained"],
        "drained_sharded": sharded["drained"],
        "serving_identity_ok": identical
        and deliveries > 0
        and single["drained"]
        and sharded["drained"],
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--blocks", type=int, default=24)
    ap.add_argument("--subs", type=int, default=120)
    ap.add_argument("--seed", type=int, default=11)
    args = ap.parse_args(argv)
    report = run_check(shards=args.shards, blocks=args.blocks, subs=args.subs, seed=args.seed)
    print(json.dumps(report))
    return 0 if report["serving_identity_ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
