"""Serving tier: backpressured notification fanout for remote consumers.

Reference: notify/src/broadcaster.rs + rpc/wrpc/server — the async stage
between the in-process Notifier chain and the RPC wire transports.
"""

from kaspa_tpu.serving.broadcaster import (  # noqa: F401
    LAG_STAGES,
    POLICIES,
    POLICY_DISCONNECT,
    POLICY_DROP_OLDEST,
    Broadcaster,
    Subscriber,
    set_stage_tracing,
    stage_tracing_enabled,
)
from kaspa_tpu.serving.pool import SenderPool  # noqa: F401
from kaspa_tpu.serving.scope_index import ScopeIndex  # noqa: F401
from kaspa_tpu.serving.shards import ShardedBroadcaster  # noqa: F401
