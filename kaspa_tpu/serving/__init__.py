"""Serving tier: backpressured notification fanout for remote consumers.

Reference: notify/src/broadcaster.rs + rpc/wrpc/server — the async stage
between the in-process Notifier chain and the RPC wire transports.
"""

from kaspa_tpu.serving.broadcaster import (  # noqa: F401
    POLICIES,
    POLICY_DISCONNECT,
    POLICY_DROP_OLDEST,
    Broadcaster,
    Subscriber,
)
