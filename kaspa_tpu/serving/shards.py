"""Sharded fanout tier: subscriber-partitioned fanout workers.

The PR 6 ``Broadcaster`` is ONE fanout thread that scope-filters every
diff against every subscriber — the serving plane's serial stage, and the
wall the PR 16 load harness measured (~2 diffs/s fanout saturation at 50k
subscribers).  This module horizontalizes it the way PR 10's fabric
scaled the verify plane: N pipelined workers behind the SAME interface,
call-site-free.

  consensus root ──> rpc Notifier ──(one wildcard listener)──> publish
                                                                  │ ingest queue
                                                         splitter thread:
                                                         index diff by script ONCE
                                                  ┌───────────┼───────────┐
                                             shard 0       shard 1  ...  shard N-1
                                             bounded q     bounded q     bounded q
                                             worker:       worker:       worker:
                                             ScopeIndex    ScopeIndex    ScopeIndex
                                             route+offer   route+offer   route+offer
                                                  │            │             │
                                             its subscribers (hash-partitioned,
                                             each with its shard's sender pool)

Two multiplications over the single-fanout path:

* **Scope pushdown** — each shard owns a ``ScopeIndex`` slice, so routing
  a diff costs O(affected subscribers), never a full-population scan; and
  subscribers sharing a matched-script set share ONE filtered payload
  (the zipf-hot case: thousands of watchers on one exchange address).
* **Partitioned workers** — subscribers are hash-partitioned by stable
  subscriber id (crc32, never Python's salted ``hash``), each shard with
  its own bounded queue and optionally its own ``SenderPool`` crew, so
  fanout work parallelizes across cores and one slow shard never blocks
  the others' offers.

Delivered streams are bit-identical to the single-fanout path —
``serving/check.py`` proves it on a recorded diff sequence, and
``daemon --fanout-shards 1`` keeps today's ``Broadcaster`` verbatim.

Lock order (utils/sync.py RANKS): serving.shards(49) facade state ->
serving.shard(51) per-shard index/membership -> serving.subscriber(55);
the shard hand-off queues are stdlib Queues whose internal lock is a leaf
(the splitter holds no ranked lock while putting, workers none while
getting), and offers happen OUTSIDE the shard lock from a membership
snapshot — the unsubscribe guarantee is enforced at the subscriber
(``Subscriber.retract``: active-event set + queued purge + in-flight
wait), not by stretching the shard lock across sink writes.
"""

from __future__ import annotations

import queue
import threading
import zlib
from time import perf_counter_ns

from kaspa_tpu.core.log import get_logger
from kaspa_tpu.notify.notifier import EVENT_TYPES, Notification
from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.serving import broadcaster as _bmod
from kaspa_tpu.serving.broadcaster import (
    _FANOUT_EVENTS,
    _INGEST_DROPS,
    _LAG_ACCEPT_TO_FANOUT,
    _LAG_MS,
    _SHARD_QUEUE_WAIT,
    Broadcaster,
    Subscriber,
)
from kaspa_tpu.serving.pool import SenderPool
from kaspa_tpu.serving.scope_index import ScopeIndex
from kaspa_tpu.utils.sync import ranked_lock

log = get_logger("serving")

_SHARD_ROUTED = REGISTRY.counter_family(
    "serving_shard_routed", "shard",
    help="subscriber offers routed by each fanout shard worker",
)


def shard_of(name: str, shards: int) -> int:
    """Stable subscriber-id -> shard partition.  crc32 (not ``hash``):
    Python string hashing is salted per process, and the partition must
    be identical across restarts and between the daemon and its tools."""
    return zlib.crc32(name.encode()) % shards


def filter_payload(n: Notification, matched: list, by_script: dict) -> Notification:
    """Scoped utxos-changed payload for a routed subscriber: byte-for-byte
    ``Broadcaster._filter_utxos_changed`` (sorted matched scripts, diff
    pairs concatenated in script order, scope set of matched scripts),
    minus the per-subscriber scope scan the index already answered."""
    matched = sorted(matched)
    added: list = []
    removed: list = []
    for s in matched:
        a, r = by_script[s]
        added.extend(a)
        removed.extend(r)
    data = dict(n.data)
    data["added"] = added
    data["removed"] = removed
    data["spk_set"] = set(matched)
    return Notification(n.event_type, data, n.ctx, t_accept_ns=n.t_accept_ns, merged=n.merged)


class _Routed:
    """One split event crossing a shard queue.  An object (not a bare
    tuple) so the payload visibly carries its trace context — the
    Notification's ``ctx`` rides inside, same as the single-fanout path's
    ingest queue."""

    __slots__ = ("n", "by_script", "t0_ns")

    def __init__(self, n: Notification, by_script: dict | None, t0_ns: int):
        self.n = n
        self.by_script = by_script
        self.t0_ns = t0_ns


class _Shard:
    """One fanout partition: scope-index slice, membership, bounded
    hand-off queue, worker thread, optional sender pool."""

    __slots__ = (
        "idx", "lock", "index", "event_subs", "subs", "q", "pool",
        "thread", "busy_ns", "events", "routed",
    )

    def __init__(self, idx: int, maxsize: int, pool: SenderPool | None):
        self.idx = idx
        self.lock = ranked_lock("serving.shard", reentrant=False)
        self.index = ScopeIndex()
        # event type -> subscriber set for everything that isn't
        # utxos-changed (those events have no scope: every subscriber of
        # the type gets the whole notification)
        self.event_subs: dict[str, set] = {}
        self.subs: list[Subscriber] = []
        self.q: queue.Queue = queue.Queue(maxsize=maxsize)
        self.pool = pool
        self.thread: threading.Thread | None = None
        # written only by this shard's worker, read by saturation probes
        self.busy_ns = 0
        self.events = 0
        self.routed = 0


class ShardedBroadcaster:
    """N-shard fanout tier behind the ``Broadcaster`` surface.

    Same call contract as ``Broadcaster``: one wildcard notifier listener
    (refcounted per event type across ALL shards), ``publish`` never
    blocks, ``subscribe``/``unsubscribe``/``register``/``unregister``
    under the daemon dispatch lock.  ``notify``/``rpc``/``wrpc`` call
    sites swap in via ``daemon --fanout-shards N`` with zero changes.

    ``pool_workers`` > 0 gives each shard its own ``SenderPool`` crew
    (``sender_pool_for(name)`` hands the right pool to the code creating
    the Subscriber); 0 keeps thread-per-subscriber senders.
    """

    def __init__(
        self,
        notifier,
        shards: int = 4,
        ingest_maxsize: int = 8192,
        shard_maxsize: int = 1024,
        pool_workers: int = 0,
        pool_batch: int = 64,
    ):
        self.notifier = notifier
        self.shard_count = max(1, int(shards))
        self._ingest: queue.Queue = queue.Queue(maxsize=ingest_maxsize)
        self._mu = ranked_lock("serving.shards", reentrant=False)
        self._conflate_floor: int | None = None
        self._event_refs: dict[str, int] = {}
        self._closed = False
        self._shards = [
            _Shard(
                i,
                shard_maxsize,
                SenderPool(workers=pool_workers, batch=pool_batch, name=f"serving-shard{i}-pool")
                if pool_workers > 0
                else None,
            )
            for i in range(self.shard_count)
        ]
        # splitter utilization (vs blocked on the ingest queue); the
        # per-shard twin lives on each _Shard
        self.split_busy_ns = 0
        self.fanout_events = 0
        self._lid = notifier.register(self.publish)
        self._splitter = threading.Thread(
            target=self._split_run, daemon=True, name="serving-splitter"
        )
        self._splitter.start()
        for sh in self._shards:
            sh.thread = threading.Thread(
                target=self._shard_run, args=(sh,), daemon=True, name=f"serving-shard-{sh.idx}"
            )
            sh.thread.start()
        _bmod.register_serving_collector(self._collect)

    # --- partitioning helpers ---

    def shard_of(self, name: str) -> int:
        return shard_of(name, self.shard_count)

    def sender_pool_for(self, name: str) -> SenderPool | None:
        """The pool a Subscriber named ``name`` must be constructed with
        (its shard's crew), or None in thread-per-subscriber mode."""
        return self._shards[self.shard_of(name)].pool

    # --- observability ---

    @property
    def fanout_busy_ns(self) -> int:
        """Total fanout-tier processing time: splitter + every shard.
        The sum (not the max) is the conservative, core-count-free
        saturation denominator — on one core all stages serialize, and on
        many cores a sum-based events/busy still lower-bounds capacity."""
        return self.split_busy_ns + sum(sh.busy_ns for sh in self._shards)

    def shard_wait_cells(self) -> list:
        """Per-shard queue_wait histogram cells in shard order — the
        overload plane maxes windowed means across these (one wedged
        shard trips ELEVATED; a global mean would dilute it)."""
        return [_SHARD_QUEUE_WAIT.cell(str(i)) for i in range(self.shard_count)]

    def shard_depths(self) -> list[int]:
        """Deepest subscriber queue per shard."""
        out = []
        for sh in self._shards:
            with sh.lock:
                subs = list(sh.subs)
            out.append(max((s.queue_depth() for s in subs), default=0))
        return out

    def max_queue_depth(self) -> int:
        """Deepest per-subscriber queue across every shard (the overload
        fanout signal aggregates max-across-shards by construction)."""
        return max(self.shard_depths(), default=0)

    def pending(self) -> int:
        """Events still inside the fanout tier's queues (ingest + shard
        hand-offs) — the load harness's drain seam."""
        return self._ingest.qsize() + sum(sh.q.qsize() for sh in self._shards)

    def senders_pending(self) -> int:
        """Subscribers queued for a drain round across shard pools."""
        return sum(sh.pool.pending() for sh in self._shards if sh.pool is not None)

    def _collect(self) -> dict:
        shards_out = []
        subs_total = delivered = dropped = conflated = 0
        depths = []
        for sh in self._shards:
            with sh.lock:
                subs = list(sh.subs)
            depth = max((s.queue_depth() for s in subs), default=0)
            depths.append(depth)
            subs_total += len(subs)
            delivered += sum(s.delivered for s in subs)
            dropped += sum(s.dropped for s in subs)
            conflated += sum(s.conflated for s in subs)
            shards_out.append(
                {
                    "shard": sh.idx,
                    "subscribers": len(subs),
                    "queue_depth": sh.q.qsize(),
                    "max_sub_depth": depth,
                    "events": sh.events,
                    "busy_ns": sh.busy_ns,
                    "routed": sh.routed,
                }
            )
        return {
            "subscribers": subs_total,
            "ingest_depth": self._ingest.qsize(),
            "max_queue_depth": max(depths, default=0),
            "dropped": dropped,
            "delivered": delivered,
            "conflated": conflated,
            "stage_tracing": int(_bmod._STAGE_TRACE),
            "fanout": {
                "events": self.fanout_events,
                "busy_ns": self.fanout_busy_ns,
                "split_busy_ns": self.split_busy_ns,
                "shards": self.shard_count,
            },
            "shards": shards_out,
            "lag_quantiles_ms": {
                stage: {
                    "count": h.count,
                    "p50": h.quantile(0.50),
                    "p99": h.quantile(0.99),
                    "p999": h.quantile(0.999),
                }
                for stage, h in sorted(_LAG_MS._cells.items())
                if h.count
            },
        }

    # --- brownout seam ---

    def set_conflation(self, floor: int | None, shard: int | None = None) -> None:
        """Arm utxos-changed diff-conflation.  ``shard=None`` arms every
        shard; a shard index arms only that partition — brownout engages
        per shard, so one pressured partition conflates while the others
        keep full-resolution diffs.  (Within a shard, conflation still
        only folds diffs for subscribers whose own queue depth reaches
        the floor.)"""
        with self._mu:
            if shard is None:
                self._conflate_floor = floor
            targets = self._shards if shard is None else [self._shards[shard]]
        for sh in targets:
            with sh.lock:
                subs = list(sh.subs)
            for s in subs:
                s.conflate_floor = floor

    # --- subscriber lifecycle (call under the daemon dispatch lock) ---

    def register(self, sub: Subscriber) -> Subscriber:
        k = self.shard_of(sub.name)
        if sub.shard is None:
            # caller built the subscriber without the shard hint (tests,
            # legacy call sites): bind it now so delivery telemetry and
            # the retract machinery engage
            sub.shard = k
            sub._shard_wait_cell = _SHARD_QUEUE_WAIT.cell(str(k))
            sub._active_events = set(sub.subscriptions)
        elif sub.shard != k:
            raise ValueError(
                f"subscriber {sub.name!r} built for shard {sub.shard} but partitions to {k}"
            )
        sh = self._shards[k]
        with sh.lock:
            sh.subs.append(sub)
            sub.conflate_floor = self._conflate_floor
        return sub

    def unregister(self, sub: Subscriber) -> None:
        """Detach a subscriber and release its upstream event refs.  The
        caller closes the subscriber (joins its thread) outside any lock."""
        sh = self._shards[self.shard_of(sub.name)]
        with sh.lock:
            if sub not in sh.subs:
                return
            sh.subs.remove(sub)
            events = list(sub.subscriptions)
            for event in events:
                scope = sub.subscriptions[event]
                if event == "utxos-changed":
                    sh.index.discard(sub, scope)
                else:
                    peers = sh.event_subs.get(event)
                    if peers is not None:
                        peers.discard(sub)
                        if not peers:
                            del sh.event_subs[event]
            sub.subscriptions = {}
        for event in events:
            self._release_event(event)
        sub.stop()

    def subscribe(self, sub: Subscriber, event: str, scripts: set | None = None) -> None:
        """Same semantics as ``Broadcaster.subscribe``: repeated
        subscribes OR scopes together, a wildcard subscribe is sticky.
        The shard's index slice is updated by delta in the same critical
        section that activates the event for delivery."""
        if event not in EVENT_TYPES:
            raise ValueError(f"unknown event type {event!r}")
        sh = self._shards[self.shard_of(sub.name)]
        with sh.lock:
            known = event in sub.subscriptions
            prev = sub.subscriptions.get(event)
            if not scripts:
                new = None  # wildcard (and sticky)
            elif known and prev is None:
                new = None  # already wildcard: narrowing via subscribe is not a thing
            else:
                base = prev if prev is not None else frozenset()
                new = base | frozenset(scripts)
            sub.subscriptions[event] = new
            if event == "utxos-changed":
                if known:
                    sh.index.update(sub, prev, new)
                else:
                    sh.index.add(sub, new)
            elif not known:
                sh.event_subs.setdefault(event, set()).add(sub)
            sub.activate(event)
        if not known:
            with self._mu:
                self._event_refs[event] = self._event_refs.get(event, 0) + 1
                first = self._event_refs[event] == 1
            if first:
                # upstream subscription stays wildcard: the splitter needs
                # the full diff to index it once for every shard
                self.notifier.start_notify(self._lid, event)

    def unsubscribe(self, sub: Subscriber, event: str) -> None:
        if event not in EVENT_TYPES:
            raise ValueError(f"unknown event type {event!r}")
        sh = self._shards[self.shard_of(sub.name)]
        with sh.lock:
            if event not in sub.subscriptions:
                return
            prev = sub.subscriptions.pop(event)
            if event == "utxos-changed":
                sh.index.discard(sub, prev)
            else:
                peers = sh.event_subs.get(event)
                if peers is not None:
                    peers.discard(sub)
                    if not peers:
                        del sh.event_subs[event]
        # the hard half of the contract: a fanout worker may hold a
        # routing snapshot that predates the index removal — retract
        # bounces those offers, purges queued entries and waits out an
        # in-flight delivery, so NOTHING of this event reaches the sink
        # after this call returns
        sub.retract(event)
        self._release_event(event)

    def _release_event(self, event: str) -> None:
        with self._mu:
            n = self._event_refs.get(event, 0) - 1
            if n > 0:
                self._event_refs[event] = n
                return
            self._event_refs.pop(event, None)
            if self._closed:
                return
        self.notifier.stop_notify(self._lid, event)

    # --- publisher side (notifier callback; must never block) ---

    def publish(self, notification: Notification) -> None:
        try:
            self._ingest.put_nowait(notification)
        except queue.Full:
            _INGEST_DROPS.inc()

    # --- splitter thread: index once, route per shard ---

    def _offer_shard(self, sh: _Shard, item: _Routed) -> None:
        # blocking put with a close-aware retry: a backed-up shard parks
        # the splitter (backpressure propagates to the ingest queue, where
        # publish drops — exactly the single-fanout overflow story)
        while True:
            try:
                sh.q.put(item, timeout=0.25)
                return
            except queue.Full:
                if self._closed:
                    return

    def _split_run(self) -> None:
        while True:
            n = self._ingest.get()
            if n is None:
                return
            t0_ns = perf_counter_ns()
            _FANOUT_EVENTS.inc(n.event_type)
            if _bmod._STAGE_TRACE and n.t_accept_ns:
                _LAG_ACCEPT_TO_FANOUT.observe((t0_ns - n.t_accept_ns) * 1e-6)
            with trace.span(
                "serving.split", parent=getattr(n, "ctx", None), event=n.event_type,
            ):
                by_script = (
                    Broadcaster._index_diff(n) if n.event_type == "utxos-changed" else None
                )
                item = _Routed(n, by_script, t0_ns)
                for sh in self._shards:
                    self._offer_shard(sh, item)
            self.fanout_events += 1
            self.split_busy_ns += perf_counter_ns() - t0_ns

    # --- shard workers: scope-index routing + offers ---

    def _shard_run(self, sh: _Shard) -> None:
        routed_cell = _SHARD_ROUTED.cell(str(sh.idx))
        while True:
            item = sh.q.get()
            if item is None:
                return
            n = item.n
            t1_ns = perf_counter_ns()
            offers = 0
            with trace.span(
                "serving.fanout", parent=getattr(n, "ctx", None),
                event=n.event_type, shard=sh.idx,
            ):
                # offers run with deferred pool kicks: subscribers needing
                # a drain are collected and handed to the shard's pool as
                # one schedule_many (one worker wakeup per chunk, not one
                # per subscriber — every pooled subscriber of this shard
                # shares sh.pool by construction)
                kicks: list = []
                if item.by_script is not None:
                    # membership snapshot under the shard lock; payload
                    # building and offers run outside it (retract closes
                    # the unsubscribe race at the subscriber)
                    with sh.lock:
                        hits = sh.index.route(item.by_script)
                        wild = list(sh.index.wildcard) if sh.index.wildcard else ()
                    cache: dict = {}
                    for sub, matched in hits.items():
                        matched.sort()
                        key = tuple(matched)
                        filtered = cache.get(key)
                        if filtered is None:
                            filtered = cache[key] = filter_payload(n, matched, item.by_script)
                        if sub.offer(filtered, item.t0_ns, defer_kick=True):
                            kicks.append(sub)
                        offers += 1
                    for sub in wild:
                        if sub.offer(n, item.t0_ns, defer_kick=True):
                            kicks.append(sub)
                        offers += 1
                else:
                    with sh.lock:
                        targets = list(sh.event_subs.get(n.event_type, ()))
                    for sub in targets:
                        if sub.offer(n, item.t0_ns, defer_kick=True):
                            kicks.append(sub)
                        offers += 1
                if kicks:
                    sh.pool.schedule_many(kicks)
            sh.events += 1
            sh.routed += offers
            if offers:
                routed_cell.inc(offers)
            sh.busy_ns += perf_counter_ns() - t1_ns

    # --- lifecycle ---

    def close(self) -> None:
        """Stop the tier: detach from the notifier, stop the splitter,
        every shard worker, every shard pool, every subscriber.  Call
        under the daemon dispatch lock (notifier mutation), like
        subscribe/unsubscribe."""
        with self._mu:
            if self._closed:
                return
            self._closed = True
            self._event_refs.clear()
        self.notifier.unregister(self._lid)
        self._ingest.put(None)
        self._splitter.join(timeout=5.0)
        for sh in self._shards:
            sh.q.put(None)
        for sh in self._shards:
            if sh.thread is not None:
                sh.thread.join(timeout=5.0)
        all_subs: list[Subscriber] = []
        for sh in self._shards:
            with sh.lock:
                all_subs.extend(sh.subs)
                sh.subs.clear()
                sh.event_subs.clear()
                sh.index.clear()
            if sh.pool is not None:
                sh.pool.close()
        for sub in all_subs:
            sub.close()
        _bmod.unregister_serving_collector(self._collect)
