"""Block-task dependency manager: out-of-order intake for the pipeline.

Same contract as the reference's BlockTaskDependencyManager
(consensus/src/pipeline/deps_manager.rs:179): tasks are grouped per block
hash; a worker that try_begins a task whose direct parent is still
pending parks the task under that parent and moves on; completing a task
releases its dependents (or the next queued duplicate of the same hash).
"""

from __future__ import annotations

import threading

from kaspa_tpu.utils.sync import ranked_lock
from collections import deque
from dataclasses import dataclass, field

from kaspa_tpu.observability.core import REGISTRY

# intake shape: how much out-of-order / duplicate traffic the deps manager
# absorbs (IBD storms show up here before they show up as stage latency)
_REGISTERED = REGISTRY.counter("deps_tasks_registered", help="task groups opened")
_ABSORBED = REGISTRY.counter("deps_duplicates_absorbed", help="same-hash submissions merged into a group")
_PARKED = REGISTRY.counter("deps_tasks_parked", help="try_begin deferrals under a pending parent")
_RELEASED = REGISTRY.counter("deps_dependents_released", help="parked tasks rescheduled by a parent completing")


@dataclass
class _TaskGroup:
    tasks: deque = field(default_factory=deque)  # same-hash duplicates, FIFO
    dependent_tasks: list = field(default_factory=list)  # hashes parked on us
    taken: bool = False  # front task handed to a worker by try_begin


class BlockTaskDependencyManager:
    def __init__(self):
        self._pending: dict[bytes, _TaskGroup] = {}
        self._mu = ranked_lock("pipeline.deps", reentrant=False)
        self._idle = self._mu.condition()

    def register(self, task_id: bytes, task) -> bool:
        """Queue `task` under `task_id`.  Returns True if the id should be
        scheduled to a worker now; False if an earlier task with the same
        hash is already pending (the group absorbs the duplicate)."""
        with self._mu:
            group = self._pending.get(task_id)
            if group is None:
                g = _TaskGroup()
                g.tasks.append(task)
                self._pending[task_id] = g
                _REGISTERED.inc()
                return True
            group.tasks.append(task)
            _ABSORBED.inc()
            return False

    def try_begin(self, task_id: bytes, parents_of) -> object | None:
        """Hand the front task of `task_id` to the calling worker, unless a
        direct parent is itself pending — then park and return None.
        ``parents_of(task)`` extracts the direct parents of the front task."""
        with self._mu:
            group = self._pending[task_id]
            assert group.tasks and not group.taken, "try_begin expects an untaken task"
            for parent in parents_of(group.tasks[0]):
                parent_group = self._pending.get(parent)
                if parent_group is not None and parent != task_id:
                    parent_group.dependent_tasks.append(task_id)
                    _PARKED.inc()
                    return None
            group.taken = True
            return group.tasks[0]

    def end(self, task_id: bytes) -> list[bytes]:
        """Mark the in-flight task of `task_id` complete.  Returns hashes to
        reschedule: the same hash if duplicates remain queued, else every
        task parked on this one."""
        with self._mu:
            group = self._pending[task_id]
            assert group.taken, "end expects the task begun via try_begin"
            group.tasks.popleft()
            group.taken = False
            if group.tasks:
                return [task_id]
            del self._pending[task_id]
            if not self._pending:
                self._idle.notify_all()
            _RELEASED.inc(len(group.dependent_tasks))
            return group.dependent_tasks

    def is_pending(self, task_id: bytes) -> bool:
        with self._mu:
            return task_id in self._pending

    def wait_for_idle(self, timeout: float | None = None) -> bool:
        with self._mu:
            if self._pending:
                return self._idle.wait(timeout)
            return True
