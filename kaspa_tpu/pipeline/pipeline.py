"""Concurrent consensus pipeline: staged workers over one consensus core.

Re-design of the reference's 4-processor pipeline (consensus/src/pipeline/:
header/body/virtual processors connected by channels, backed by a block
task dependency manager) for the Python+TPU runtime:

- An intake that registers submissions with the dependency manager, so
  blocks may arrive out of order and duplicates collapse into task groups
  (deps_manager.rs semantics, ported in pipeline/deps_manager.py).
- A pool of stage workers running header+body validation.  The
  GIL-releasing parts — header/tx hashing (hashlib), batch marshalling
  (numpy), device dispatch (XLA) — overlap across threads; the
  pure-Python consensus math serializes under one ranked commit lock
  (an honest mapping of the reference's rayon pools onto the Python
  runtime; see utils/sync.py LockCtx for the deadlock-detection story).
- A single virtual worker (the reference also serializes virtual state):
  it *drains* its queue each cycle, updates tips for every completed
  block, then resolves virtual once — so device signature batches under
  chain verification draw from all in-flight blocks of the cycle instead
  of dispatching per block (virtual_processor/processor.rs:267-271 task
  batching).

``submit`` returns a Future resolving to the block's status after the
virtual stage absorbed it (the reference's virtual_state_task).
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import Future
from dataclasses import dataclass
from time import perf_counter_ns

from kaspa_tpu.consensus.stores import StatusesStore
from kaspa_tpu.observability import flight, trace
from kaspa_tpu.observability.core import DEFAULT_LATENCY_BUCKETS, REGISTRY, SIZE_BUCKETS
from kaspa_tpu.pipeline.deps_manager import BlockTaskDependencyManager
from kaspa_tpu.pipeline.speculative import SpeculativeVerifier
from kaspa_tpu.utils.sync import Channel, Closed, LockCtx, ranked_lock

# queue wait vs execute split per stage — the question the round-5 bench
# failure could not answer ("which stage stalled?")
_Q_WAIT = REGISTRY.histogram_family(
    "pipeline_queue_wait_seconds", "stage", DEFAULT_LATENCY_BUCKETS,
    help="time a task sat queued before a worker picked it up",
)
_LOCK_WAIT = REGISTRY.histogram(
    "pipeline_commit_lock_wait_seconds", DEFAULT_LATENCY_BUCKETS,
    help="time stage workers waited on the ranked commit lock",
)
_VIRT_BATCH = REGISTRY.histogram(
    "pipeline_virtual_batch_size", SIZE_BUCKETS,
    help="blocks absorbed per virtual-resolution cycle",
)
_SUBMITTED = REGISTRY.counter("pipeline_tasks_submitted", help="blocks entered into the pipeline")


@dataclass
class _Task:
    block: object  # Block (or header-only Block with empty txs)
    header_only: bool
    future: Future
    enqueue_ns: int = 0  # set at submit / virtual hand-off for queue-wait spans
    ctx: object = None  # flight-recorder root TraceContext (None when off)


class ConsensusPipeline:
    def __init__(self, consensus, workers: int = 2, speculative: bool | None = None):
        self.consensus = consensus
        self.deps = BlockTaskDependencyManager()
        self._ready = Channel()
        self._virtual_q = Channel()
        self._lock = LockCtx("consensus-commit", rank=10)
        # bound the blocks absorbed per virtual cycle: a deep IBD burst must
        # not collapse into one giant resolve with unbounded commit latency
        self._virtual_batch_max = max(1, int(os.environ.get("KASPA_TPU_VIRTUAL_BATCH_MAX", "64")))
        if speculative is None:
            speculative = os.environ.get("KASPA_TPU_SPECULATIVE", "1") not in ("0", "off", "false")
        self.speculative = SpeculativeVerifier(consensus, self._lock) if speculative else None
        consensus.speculative = self.speculative
        self._inflight = 0
        self._idle_mu = ranked_lock("pipeline.idle", reentrant=False)
        self._idle_cv = self._idle_mu.condition()
        self._workers = [
            threading.Thread(target=self._stage_worker, name=f"kaspa-stage-{i}", daemon=True)
            for i in range(max(1, workers))
        ]
        self._virtual_worker_t = threading.Thread(
            target=self._virtual_worker, name="kaspa-virtual", daemon=True
        )
        for t in self._workers:
            t.start()
        self._virtual_worker_t.start()

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------

    def submit(self, block, header_only: bool = False) -> Future:
        """Queue a block for full processing; returns a Future[str status].

        Out-of-order safe: if a direct parent is itself in flight, this
        task parks until the parent completes.  Duplicate submissions of
        the same hash are absorbed into one task group and each receives
        its own result.
        """
        fut: Future = Future()
        task = _Task(block, header_only, fut, enqueue_ns=perf_counter_ns())
        # flight recorder: the block's trace starts at intake and is sealed
        # when the future resolves (after virtual absorption); a duplicate
        # submission re-joins the existing open trace
        task.ctx = flight.begin(block.hash) if flight.enabled() else None
        _SUBMITTED.inc()
        with self._idle_mu:
            self._inflight += 1
        fut.add_done_callback(self._on_done)
        if task.ctx is not None:
            fut.add_done_callback(
                lambda f, h=block.hash: flight.end(h, "error" if f.exception() else "ok")
            )
        if self.deps.register(block.hash, task):
            try:
                self._ready.send(block.hash)
            except Closed:
                self._fail_group(block.hash, RuntimeError("pipeline shut down"))
        return fut

    def validate_and_insert_block(self, block) -> str:
        """Synchronous submission (raises the pipeline error, if any)."""
        return self.submit(block).result()

    def wait_for_idle(self, timeout: float | None = 60.0) -> None:
        with self._idle_mu:
            self._idle_cv.wait_for(lambda: self._inflight == 0, timeout)

    def shutdown(self) -> None:
        self._ready.close()
        for t in self._workers:
            t.join(timeout=10)
        self._virtual_q.close()
        self._virtual_worker_t.join(timeout=10)
        # detach: direct (serial) callers of _verify_chain_block after
        # shutdown must not consume stale entries
        self.consensus.speculative = None

    # ------------------------------------------------------------------
    # stage workers: header + body
    # ------------------------------------------------------------------

    def _on_done(self, _fut) -> None:
        with self._idle_mu:
            self._inflight -= 1
            if self._inflight == 0:
                self._idle_cv.notify_all()

    def _requeue(self, ids) -> None:
        for dep in ids:
            try:
                self._ready.send(dep)
            except Closed:
                # shutdown with tasks in flight: fail the parked group so no
                # caller hangs on an unresolved future
                self._fail_group(dep, RuntimeError("pipeline shut down"))

    def _fail_group(self, task_id: bytes, err: Exception) -> None:
        with self.deps._mu:
            group = self.deps._pending.pop(task_id, None)
            if group is None:
                return
            tasks, dependents = list(group.tasks), list(group.dependent_tasks)
            if not self.deps._pending:
                self.deps._idle.notify_all()
        for t in tasks:
            if not t.future.done():
                t.future.set_exception(err)
        for dep in dependents:
            self._fail_group(dep, err)

    def _stage_worker(self) -> None:
        consensus = self.consensus
        for task_id in self._ready:
            task = self.deps.try_begin(task_id, lambda t: t.block.header.direct_parents())
            if task is None:
                continue  # parked under a pending parent
            now = perf_counter_ns()
            _Q_WAIT.observe("stage", (now - task.enqueue_ns) * 1e-9)
            # queue wait as a first-class span so critical-path attribution
            # names the handoff latency instead of losing it to root self-time
            trace.record_span("wait.stage", task.ctx, task.enqueue_ns, now)
            duplicate_status = None
            err = None
            try:
                with trace.span("pipeline.stage", parent=task.ctx):
                    # GIL-releasing precompute outside the commit lock: header
                    # hash + merkle leaves hash concurrently across workers
                    blk = task.block
                    with trace.span("pipeline.precompute"):
                        _ = blk.hash
                        if not task.header_only:
                            for tx in blk.transactions:
                                tx.id()
                    t_lock = perf_counter_ns()
                    with self._lock:
                        _LOCK_WAIT.observe((perf_counter_ns() - t_lock) * 1e-9)
                        with trace.span("pipeline.commit"):
                            existing = consensus.storage.statuses.get(blk.hash)
                            if existing is not None and (
                                task.header_only or existing != StatusesStore.STATUS_HEADER_ONLY
                            ):
                                duplicate_status = existing  # no reprocessing
                            else:
                                with trace.span("pipeline.header"):
                                    if consensus._process_header(blk.header):
                                        consensus.counters.inc_headers()
                                if task.header_only:
                                    consensus.storage.flush()
                                else:
                                    consensus.counters.inc_blocks_submitted()
                                    with trace.span("pipeline.body"):
                                        consensus._process_body(blk)
                                    consensus.counters.inc_bodies()
                                    consensus.counters.inc_txs(len(blk.transactions))
            except Exception as e:
                err = e
            # on success, hand the task to the virtual queue BEFORE releasing
            # dependents: a child finishing its stages can then never overtake
            # its parent into tips/virtual resolution
            if err is None and duplicate_status is None and not task.header_only:
                # speculative chain-state precompute runs BEFORE the virtual
                # hand-off, so by the time the virtual worker verifies this
                # block its (block, selected_parent) entry is already cached;
                # device waits happen here, off the commit lock, coalescing
                # with other speculating workers' script batches
                if self.speculative is not None:
                    self.speculative.run(blk.hash, task.ctx)
                try:
                    task.enqueue_ns = perf_counter_ns()
                    self._virtual_q.send(task)
                except Closed:
                    err = RuntimeError("pipeline shut down")
            self._requeue(self.deps.end(task_id))
            if err is not None:
                task.future.set_exception(err)
            elif duplicate_status is not None:
                task.future.set_result(duplicate_status)
            elif task.header_only:
                task.future.set_result(consensus.storage.statuses.get(blk.hash))

    # ------------------------------------------------------------------
    # virtual worker
    # ------------------------------------------------------------------

    def _virtual_worker(self) -> None:
        consensus = self.consensus
        while True:
            try:
                first = self._virtual_q.recv()
            except Closed:
                return
            batch = [first] + self._virtual_q.drain(self._virtual_batch_max - 1)
            now = perf_counter_ns()
            _VIRT_BATCH.observe(len(batch))
            for task in batch:
                _Q_WAIT.observe("virtual", (now - task.enqueue_ns) * 1e-9)
                trace.record_span("wait.virtual", task.ctx, task.enqueue_ns, now)
            t_lock = perf_counter_ns()
            with self._lock:
                _LOCK_WAIT.observe((perf_counter_ns() - t_lock) * 1e-9)
                try:
                    # the TLS span parents on the first task's trace: muhash /
                    # store.flush / utxoindex children nest there; every other
                    # task in the batch gets a synthetic same-interval span so
                    # its trace still owns the shared virtual-cycle time
                    t_v0 = perf_counter_ns()
                    with trace.span("pipeline.virtual", parent=batch[0].ctx, batch=len(batch)):
                        for task in batch:
                            consensus.notification_root.notify_block_added(task.block, task.ctx)
                            consensus._update_tips(task.block.hash)
                        # one virtual resolution absorbs the whole cycle: chain
                        # verification batches signatures across these blocks
                        # graftlint: allow(blocking-under-lock) -- the virtual cycle's device work runs under the pipeline lock by design: the pipeline thread is the sole consumer and the watchdog monitors progress
                        consensus._resolve_virtual()
                        consensus.storage.flush()
                    t_v1 = perf_counter_ns()
                    for task in batch[1:]:
                        trace.record_span(
                            "pipeline.virtual", task.ctx, t_v0, t_v1,
                            batch=len(batch), shared=True,
                        )
                except Exception as e:
                    for task in batch:
                        if not task.future.done():
                            task.future.set_exception(e)
                    continue
                for task in batch:
                    status = consensus.storage.statuses.get(task.block.hash)
                    if not task.future.done():
                        task.future.set_result(status)
