"""Speculative chain-state precompute: chain verification off the virtual lock.

The flight recorder's critical-path tables (PR 7) attribute the bulk of
per-block wall time to ``pipeline.virtual``, and the profile underneath is
unambiguous: `_resolve_virtual` serially redoes `_calculate_utxo_state`
for every chain candidate — mergeset replay, the batched script checks,
the muhash device product — while the stage workers idle.  This module
moves that compute onto the stage workers, as the reference moves it onto
rayon (virtual_processor/processor.rs calculate_utxo_state rayon pools):

- When a block's body commits and its selected parent's UTXO state is
  *reachable* — the live ``utxo_position``, or a pending speculative entry
  for the parent (chained speculation) — the stage worker immediately
  computes the block's chain-verification context and caches it keyed by
  ``(block, selected_parent)``.
- `_verify_chain_block` (virtual worker) pops the entry on a hit and goes
  straight to the five header checks + commit; on a miss it recomputes
  synchronously.  Hit and miss paths produce bit-identical state.
- Script checks route through the block's own ``BatchScriptChecker`` into
  the coalescing dispatcher (`ops/dispatch.py`), so concurrently
  speculating blocks merge into one device super-batch.

Safety invariants (these are what make hit == miss bit-identical):

1. Every consensus-state read happens in ``_begin`` **under the pipeline's
   commit lock** — the same lock serializing `_resolve_virtual`, header
   commits and every `_move_utxo_position` — so speculation observes
   exactly the frozen state the synchronous path would.  The device waits
   (script super-batch, muhash product) run outside the lock and touch
   only entry-private data (the staged jobs, a cloned multiset).
2. Script checks are staged *optimistically*: every staged tx is assumed
   accepted.  If any staged check fails after the async dispatch resolves,
   the whole entry is discarded — the synchronous fallback recomputes and
   reaches the identical (disqualify) verdict the honest path would.
3. The cache key ``(block, selected_parent)`` is position-proof: the UTXO
   state at a given position is a pure function of the position, so an
   entry survives reorgs away-and-back and is consumed whenever
   `_verify_chain_block` runs with ``utxo_position == selected_parent``.
4. A *chained* entry (parent state read from another pending entry's
   optimistic diff instead of the live set) is only consumable after that
   parent entry itself committed via the cache — which proves the
   optimistic parent diff equals the committed one.  A parent that fell
   back to the synchronous path leaves the child entry unconsumed
   (invalidated), never wrongly trusted.
5. Toccata-active blocks are never speculated: their VM-fallback lane
   reads reachability through the seq-commit accessor on pool threads,
   which is only safe while the dispatching thread holds the commit lock
   (the synchronous path does; the speculative wait phase deliberately
   does not).
"""

from __future__ import annotations

import threading

from kaspa_tpu.utils.sync import ranked_lock
from dataclasses import dataclass, field

from kaspa_tpu.consensus.processes.transaction_validator import FLAG_FULL
from kaspa_tpu.consensus.stores import StatusesStore
from kaspa_tpu.consensus.utxo import UtxoView
from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import DEFAULT_LATENCY_BUCKETS, REGISTRY

_HITS = REGISTRY.counter(
    "speculative_hits", help="chain verifications served from the speculative precompute cache"
)
_MISSES = REGISTRY.counter(
    "speculative_misses", help="chain verifications that recomputed synchronously (no usable entry)"
)
_INVALIDATIONS = REGISTRY.counter_family(
    "speculative_invalidations", "reason",
    help="speculative entries discarded before use (script failure, uncommitted parent, error)",
)
_PRECOMPUTES = REGISTRY.counter(
    "speculative_precomputes", help="speculative chain-state contexts computed by stage workers"
)
_INELIGIBLE = REGISTRY.counter_family(
    "speculative_ineligible", "reason",
    help="blocks that skipped speculation at begin time (position unreachable, toccata, dup)",
)
_WAIT = REGISTRY.histogram(
    "speculative_wait_seconds", DEFAULT_LATENCY_BUCKETS,
    help="off-lock device wait per speculative precompute (scripts + muhash)",
)


@dataclass
class _Entry:
    block: bytes
    selected_parent: bytes
    ctx: dict
    # the state view this entry's descendants chain onto: selected-parent
    # base composed with this entry's (optimistic == committed) diff
    view: UtxoView
    parent_entry: "_Entry | None"
    # position at the bottom of the entry's chain — the live utxo_position
    # every read in the chain was frozen against
    base_position: bytes


@dataclass
class _Pending:
    block: bytes
    selected_parent: bytes
    gd: object
    ctx: dict
    base: object
    parent_entry: _Entry | None
    base_position: bytes
    handle: object  # DispatchHandle
    txs: list
    own_staged: list
    trace_ctx: object = None
    script_failed: bool = field(default=False)


class SpeculativeVerifier:
    """One per ConsensusPipeline; attached as ``consensus.speculative``."""

    # chained entries nest UtxoViews one level per ancestor; bound the walk
    MAX_CHAIN_DEPTH = 16
    MAX_ENTRIES = 256

    def __init__(self, consensus, commit_lock):
        self.consensus = consensus
        self._commit_lock = commit_lock
        self._mu = ranked_lock("pipeline.speculative", reentrant=False)
        self._entries: dict[tuple[bytes, bytes], _Entry] = {}  # insertion-ordered for LRU bound
        self._by_block: dict[bytes, _Entry] = {}

    # ------------------------------------------------------------------
    # producer side (stage workers)
    # ------------------------------------------------------------------

    def run(self, block_hash: bytes, trace_ctx=None) -> None:
        """Full speculation attempt for one body-complete block.  Never
        raises: speculation is an optimization, every failure degrades to
        the synchronous path."""
        try:
            with trace.span("speculative.precompute", parent=trace_ctx):
                pending = self._begin(block_hash)
                if pending is None:
                    return
                self._wait(pending)
                self._finish(pending)
        except Exception:  # noqa: BLE001 - never let speculation fail a block
            _INVALIDATIONS.inc("error")

    def _begin(self, block_hash: bytes) -> _Pending | None:
        """Collect phase, under the commit lock: frozen-state reads, the
        optimistic mergeset replay, async script submission."""
        c = self.consensus
        with trace.span("speculative.begin"):
            with self._commit_lock:
                if c.storage.statuses.get(block_hash) != StatusesStore.STATUS_UTXO_PENDING_VERIFICATION:
                    _INELIGIBLE.inc("status")
                    return None
                gd = c.storage.ghostdag.get(block_hash)
                sp = gd.selected_parent
                header = c.storage.headers.get(block_hash)
                if c.params.toccata_active(header.daa_score):
                    _INELIGIBLE.inc("toccata")
                    return None
                with self._mu:
                    if (block_hash, sp) in self._entries:
                        _INELIGIBLE.inc("duplicate")
                        return None
                    parent_entry = None if sp == c.utxo_position else self._by_block.get(sp)
                if sp == c.utxo_position:
                    base = c.utxo_set
                    seed = c.multisets[sp]
                    base_position = sp
                elif parent_entry is not None:
                    # the chain of views bottoms out on the live utxo_set; the
                    # composed reads stay correct while the live position sits
                    # anywhere ON that chain (base, or a committed prefix block
                    # — applying an entry's own diff to the base leaves reads
                    # through its view unchanged), and diverge the moment it
                    # reorgs onto a different branch
                    depth, cur, on_chain = 1, parent_entry, {parent_entry.block}
                    while cur.parent_entry is not None:
                        cur = cur.parent_entry
                        on_chain.add(cur.block)
                        depth += 1
                        if depth > self.MAX_CHAIN_DEPTH:
                            _INELIGIBLE.inc("depth")
                            return None
                    on_chain.add(cur.base_position)
                    if c.utxo_position not in on_chain:
                        _INELIGIBLE.inc("position")
                        return None
                    base = parent_entry.view
                    seed = parent_entry.ctx["multiset"]
                    base_position = cur.base_position
                else:
                    _INELIGIBLE.inc("position")
                    return None

                checker = c.transaction_validator.new_checker()
                # graftlint: allow(blocking-under-lock) -- unreachable sync branch: checker is supplied, so _validate_transactions inside never takes its synchronous dispatch() path here
                ctx = c._calculate_utxo_state(
                    gd, header.daa_score, base=base, seed_multiset=seed, checker=checker
                )
                # check-5 staging (own txs over the block's own view): same
                # checker, so one async submission covers the whole block
                txs = c.storage.block_transactions.get(block_hash)
                own_view = UtxoView(base, ctx["mergeset_diff"])
                own_staged = c._validate_transactions(  # graftlint: allow(blocking-under-lock) -- unreachable sync branch: _begin passes checker=dispatch_async, _validate_transactions only calls dispatch() when no async checker is supplied
                    txs, own_view, header.daa_score, FLAG_FULL,
                    checker=checker, token_tag=("own",), position_anchor=sp,
                )
                handle = checker.dispatch_async()
        return _Pending(
            block=block_hash, selected_parent=sp, gd=gd, ctx=ctx, base=base,
            parent_entry=parent_entry, base_position=base_position,
            handle=handle, txs=txs, own_staged=own_staged,
        )

    def _wait(self, p: _Pending) -> None:
        """Device phase, no locks held: join the (coalesced) script
        super-batch, then reduce the entry-private muhash product."""
        from time import perf_counter_ns

        t0 = perf_counter_ns()
        with trace.span("speculative.wait"):
            results = p.handle.result()
            for token in p.ctx["staged_tokens"]:
                if results.get(token) is not None:
                    p.script_failed = True
            for token, _tx, _e, _f in p.own_staged:
                if results.get(token) is not None:
                    p.script_failed = True
            if not p.script_failed:
                p.ctx["multiset"].add_transactions_batch(p.ctx.pop("multiset_items"))
        _WAIT.observe((perf_counter_ns() - t0) * 1e-9)

    def _finish(self, p: _Pending) -> None:
        """Publish phase: cache the entry, or discard on any optimism
        mismatch (the synchronous fallback reaches the same verdict)."""
        if p.script_failed:
            _INVALIDATIONS.inc("script")
            return
        if len(p.own_staged) < len(p.txs) - 1:
            # a non-coinbase tx failed pre-script validation: the block will
            # be disqualified either way; let the honest path do it
            _INVALIDATIONS.inc("own_txs")
            return
        p.ctx.pop("staged_tokens", None)
        entry = _Entry(
            block=p.block,
            selected_parent=p.selected_parent,
            ctx=p.ctx,
            view=UtxoView(p.base, p.ctx["mergeset_diff"]),
            parent_entry=p.parent_entry,
            base_position=p.base_position,
        )
        self._publish(entry)

    def _publish(self, entry: _Entry) -> None:
        with self._mu:
            self._entries[(entry.block, entry.selected_parent)] = entry
            self._by_block[entry.block] = entry
            while len(self._entries) > self.MAX_ENTRIES:
                oldest = next(iter(self._entries))
                old = self._entries.pop(oldest)
                if self._by_block.get(old.block) is old:
                    del self._by_block[old.block]
        _PRECOMPUTES.inc()

    # ------------------------------------------------------------------
    # in-cycle batched precompute (virtual worker, commit lock held)
    # ------------------------------------------------------------------

    def precompute_chain(self, chain: list[bytes]) -> None:
        """Batched precompute for a pending selected-chain segment, called
        by `_ensure_chain_utxo_valid` before its per-block verify loop (the
        commit lock is already held; LockCtx wraps an RLock).

        The stage-time path speculates one block per checker; here the
        cycle already knows the exact chain it must verify, so every
        *missing* (block, selected_parent) context is computed chained —
        block i+1's mergeset replays over block i's optimistic view — and
        all their script checks go to the device as ONE coalesced
        dispatch.  Without this, each cache miss inside the cycle pays a
        full synchronous dispatch serially under the commit lock, and the
        misses compound: a long cycle starves stage-time speculation
        (workers stall on the lock, then find the position moved), which
        makes the next cycle long too.

        Publication is prefix-only: a script failure at block i poisons
        the views every later block chained on, so i and everything after
        fall back to the synchronous path (which reaches the honest
        disqualify verdict)."""
        c = self.consensus
        try:
            gd0 = c.storage.ghostdag.get(chain[0])
            # identical to what _verify_chain_block(chain[0]) does first;
            # doing it here freezes the base the whole segment chains on
            c._move_utxo_position(gd0.selected_parent)
            checker = c.transaction_validator.new_checker()
            prev_block = gd0.selected_parent
            prev_view = None
            prev_seed = None
            pendings = []
            with trace.span("speculative.chain_precompute", blocks=len(chain)):
                for b in chain:
                    gd = c.storage.ghostdag.get(b)
                    sp = gd.selected_parent
                    if sp != prev_block:
                        break
                    if c.storage.statuses.get(b) != StatusesStore.STATUS_UTXO_PENDING_VERIFICATION:
                        break
                    header = c.storage.headers.get(b)
                    if c.params.toccata_active(header.daa_score):
                        break
                    with self._mu:
                        existing = self._entries.get((b, sp))
                    if existing is not None:
                        # stage-time hit: chain the rest of the segment on it
                        prev_block, prev_view, prev_seed = b, existing.view, existing.ctx["multiset"]
                        continue
                    base = prev_view if prev_view is not None else c.utxo_set
                    seed = prev_seed if prev_seed is not None else c.multisets[sp]
                    ctx = c._calculate_utxo_state(
                        gd, header.daa_score, base=base, seed_multiset=seed,
                        checker=checker, token_ns=b,
                    )
                    # muhash finalized eagerly: the next block's seed must
                    # already contain this mergeset
                    ctx["multiset"].add_transactions_batch(ctx.pop("multiset_items"))
                    txs = c.storage.block_transactions.get(b)
                    view = UtxoView(base, ctx["mergeset_diff"])
                    own_staged = c._validate_transactions(
                        txs, view, header.daa_score, FLAG_FULL,
                        checker=checker, token_tag=("own", b), position_anchor=sp,
                    )
                    pendings.append((b, sp, ctx, view, txs, own_staged))
                    prev_block, prev_view, prev_seed = b, view, ctx["multiset"]
                if not pendings:
                    return
                results = checker.dispatch_async().result()
            for b, sp, ctx, view, txs, own_staged in pendings:
                failed = (
                    any(results.get(t) is not None for t in ctx["staged_tokens"])
                    or any(results.get(t) is not None for t, _tx, _e, _f in own_staged)
                    or len(own_staged) < len(txs) - 1
                )
                if failed:
                    _INVALIDATIONS.inc("script")
                    break
                ctx.pop("staged_tokens", None)
                # parent_entry=None / base_position=sp is the conservative
                # encoding: later chaining onto this entry requires the live
                # position to be the entry's block or its selected parent —
                # both idempotent read positions for its view stack
                self._publish(_Entry(
                    block=b, selected_parent=sp, ctx=ctx, view=view,
                    parent_entry=None, base_position=sp,
                ))
        except Exception:  # noqa: BLE001 - precompute is an optimization only
            _INVALIDATIONS.inc("error")

    # ------------------------------------------------------------------
    # consumer side (virtual worker, inside _verify_chain_block)
    # ------------------------------------------------------------------

    def take(self, block: bytes, selected_parent: bytes) -> _Entry | None:
        """Pop a usable entry for (block, position==selected_parent), or
        None (synchronous recompute).  Counts the hit/miss."""
        with self._mu:
            entry = self._entries.pop((block, selected_parent), None)
            if entry is not None and self._by_block.get(block) is entry:
                del self._by_block[block]
        if entry is None:
            _MISSES.inc()
            return None
        # no parent-commit-path guard is needed here: a published entry's ctx
        # is a pure function of (block, selected_parent) — publication proves
        # every staged script passed, so the optimistic diffs it chained on
        # equal the committed ones whichever path (cache or synchronous)
        # actually committed them — and the caller just moved utxo_position
        # to selected_parent, which is exactly the state the ctx was
        # computed against
        _HITS.inc()
        return entry

    @staticmethod
    def snapshot() -> dict:
        """Process-wide speculation counters (sim/roundcheck surface)."""
        hits = _HITS.value
        misses = _MISSES.value
        total = hits + misses
        return {
            "hits": hits,
            "misses": misses,
            "precomputes": _PRECOMPUTES.value,
            "invalidations": _INVALIDATIONS.snapshot(),
            "ineligible": _INELIGIBLE.snapshot(),
            "hit_rate": round(hits / total, 4) if total else None,
        }
