"""256-bit Sparse Merkle Tree with collapsed single-leaf subtrees and
bitmap-compressed proofs (KIP-21).

Reference: crypto/smt/src/{lib,tree,proof}.rs.  Semantics:

- Keys are 32-byte hashes; bit 0 = MSB of byte 0 (root split), bit 255 =
  LSB of byte 31 (leaf split).
- A subtree holding exactly one leaf is *collapsed* to a single node with
  hash ``CollapsedHasher(key || leaf_hash)`` — domain-separated from the
  internal ``NodeHasher(left || right)`` to kill branch/collapsed second
  preimages.  An empty subtree at height i hashes to EMPTY_HASHES[i]
  (EMPTY_HASHES[0] = ZERO_HASH).
- Proofs carry a 256-bit bitmap marking which siblings along the
  root->terminal path are non-empty, only the non-empty sibling hashes,
  and a terminal describing where traversal stopped: the queried leaf, a
  collapsed subtree containing the queried key, a collapsed subtree owned
  by a *different* key (non-inclusion witness), or an empty subtree.
"""

from __future__ import annotations

from dataclasses import dataclass

from kaspa_tpu.crypto.blake3 import blake3_keyed, domain_key

DEPTH = 256
ZERO_HASH = b"\x00" * 32


class SmtError(Exception):
    pass


def bit_at(key: bytes, d: int) -> bool:
    """Big-endian bit order (lib.rs:59): True = right branch."""
    return key[d >> 3] & (0x80 >> (d & 7)) != 0


class SmtHasher:
    """A node/collapsed hasher pair with the per-level empty-hash table."""

    def __init__(self, node_domain: bytes, collapsed_domain: bytes):
        self._node_key = domain_key(node_domain)
        self._collapsed_key = domain_key(collapsed_domain)
        table = [ZERO_HASH]
        for _ in range(DEPTH):
            table.append(self.hash_node(table[-1], table[-1]))
        self.empty_hashes = table  # [height] -> hash of an empty subtree

    def hash_node(self, left: bytes, right: bytes) -> bytes:
        return blake3_keyed(self._node_key, left + right)

    def hash_collapsed(self, key: bytes, leaf_hash: bytes) -> bytes:
        return blake3_keyed(self._collapsed_key, key + leaf_hash)

    def empty_root(self) -> bytes:
        return self.empty_hashes[DEPTH]


# the KIP-21 active-lanes tree hasher (hashers.rs SeqCommitActiveNode /
# SeqCommitActiveCollapsedNode)
SEQ_COMMIT_ACTIVE = SmtHasher(b"SeqCommitActiveNode", b"SeqCommitActiveCollapsedNode")


@dataclass
class SmtProof:
    """Bitmap-compressed membership/non-membership proof.

    ``bitmap`` bit d (big-endian, like key bits) is set iff the sibling at
    depth d is non-empty; ``siblings`` lists those hashes root-first.
    ``terminal`` is one of:
      ("leaf",)                      — path descended all 256 levels
      ("collapsed", depth)           — stopped at a collapsed node owning
                                        the queried key
      ("collapsed_other", depth, foreign_key, foreign_leaf)
                                     — a different key owns the subtree
      ("empty", depth)               — the subtree at `depth` is empty
    """

    bitmap: bytes  # 32 bytes
    siblings: list
    terminal: tuple

    def terminal_depth(self) -> int:
        kind = self.terminal[0]
        if kind == "leaf":
            return DEPTH
        return self.terminal[1]

    def compute_root(self, hasher: SmtHasher, key: bytes, leaf_hash) -> bytes:
        """Fold the path back to a root.  ``leaf_hash`` of None means the
        caller asserts non-membership (terminal must be empty or owned by a
        foreign key).  Structurally malformed proofs raise SmtError; the
        encoding is canonical (bits at or beyond the terminal depth must be
        clear) so byte-distinct proofs cannot verify for the same fact."""
        if len(self.bitmap) != 32:
            raise SmtError(f"bitmap must be 32 bytes, got {len(self.bitmap)}")
        kind = self.terminal[0] if self.terminal else None
        expected_arity = {"leaf": 1, "collapsed": 2, "collapsed_other": 4, "empty": 2}.get(kind)
        if expected_arity is None or len(self.terminal) != expected_arity:
            raise SmtError(f"malformed terminal {self.terminal!r}")
        if kind == "collapsed_other" and (
            len(self.terminal[2]) != 32 or len(self.terminal[3]) != 32
        ):
            raise SmtError("malformed foreign terminal")
        depth = self.terminal_depth()
        if not (0 <= depth <= DEPTH):
            raise SmtError(f"terminal depth {depth} out of range")
        for d in range(depth, DEPTH):
            if self.bitmap[d >> 3] & (0x80 >> (d & 7)):
                raise SmtError("non-canonical bitmap: bit set beyond terminal depth")
        if kind == "empty" and depth > 0 and not (self.bitmap[(depth - 1) >> 3] & (0x80 >> ((depth - 1) & 7))):
            # an empty terminal under an empty sibling re-encodes one level
            # shallower; pin the depth to the shallowest empty subtree
            raise SmtError("non-canonical empty terminal: parent sibling also empty")
        if kind == "leaf":
            if leaf_hash is None:
                raise SmtError("membership proof requires a leaf hash")
            cur = leaf_hash
        elif kind == "collapsed":
            if leaf_hash is None:
                raise SmtError("membership proof requires a leaf hash")
            cur = hasher.hash_collapsed(key, leaf_hash)
        elif kind == "collapsed_other":
            foreign_key, foreign_leaf = self.terminal[2], self.terminal[3]
            if leaf_hash is not None:
                raise SmtError("non-membership terminal with a leaf hash")
            if foreign_key == key:
                raise SmtError("foreign terminal claims the queried key")
            # the foreign key must actually live in this subtree
            for d in range(depth):
                if bit_at(foreign_key, d) != bit_at(key, d):
                    raise SmtError("foreign key outside the terminal subtree")
            cur = hasher.hash_collapsed(foreign_key, foreign_leaf)
        elif kind == "empty":
            if leaf_hash is not None:
                raise SmtError("non-membership terminal with a leaf hash")
            cur = hasher.empty_hashes[DEPTH - depth]
        else:
            raise SmtError(f"unknown terminal {kind}")

        sib_iter = iter(reversed(self.siblings))
        expected_non_empty = sum(
            1 for d in range(depth) if self.bitmap[d >> 3] & (0x80 >> (d & 7))
        )
        if expected_non_empty != len(self.siblings):
            raise SmtError("sibling count does not match bitmap")
        for d in range(depth - 1, -1, -1):
            non_empty = self.bitmap[d >> 3] & (0x80 >> (d & 7))
            if non_empty:
                sibling = next(sib_iter)
                if sibling == hasher.empty_hashes[DEPTH - d - 1]:
                    # explicit empty-hash siblings would make the encoding
                    # malleable against the bitmap's implicit form
                    raise SmtError("non-canonical proof: explicit empty sibling")
            else:
                sibling = hasher.empty_hashes[DEPTH - d - 1]
            if bit_at(key, d):
                cur = hasher.hash_node(sibling, cur)
            else:
                cur = hasher.hash_node(cur, sibling)
        return cur

    def verify(self, hasher: SmtHasher, key: bytes, leaf_hash, root: bytes) -> bool:
        try:
            return self.compute_root(hasher, key, leaf_hash) == root
        except (SmtError, IndexError, TypeError):
            return False  # malformed peer-supplied proofs reject, never raise


class SparseMerkleTree:
    """In-memory SMT (tree.rs SparseMerkleTree): a sorted-leaf functional
    core — roots and proofs are computed by recursive key-bit splits over
    the sorted leaf list, with single-leaf subtrees collapsing."""

    def __init__(self, hasher: SmtHasher = SEQ_COMMIT_ACTIVE):
        self.hasher = hasher
        self._leaves: dict[bytes, bytes] = {}

    def insert(self, key: bytes, leaf_hash: bytes) -> None:
        assert len(key) == 32 and len(leaf_hash) == 32
        self._leaves[key] = leaf_hash

    def delete(self, key: bytes) -> None:
        self._leaves.pop(key, None)

    def get(self, key: bytes):
        return self._leaves.get(key)

    def __len__(self) -> int:
        return len(self._leaves)

    def root(self) -> bytes:
        items = sorted(self._leaves.items())
        return self._subtree_hash(items, 0)

    def _subtree_hash(self, items, depth: int) -> bytes:
        if not items:
            return self.hasher.empty_hashes[DEPTH - depth]
        if len(items) == 1:
            key, leaf = items[0]
            # at full key depth the node IS the leaf (proof.rs Leaf
            # terminal seeds with the raw leaf hash); above it, a
            # single-leaf subtree collapses
            return leaf if depth == DEPTH else self.hasher.hash_collapsed(key, leaf)
        if depth == DEPTH:
            raise SmtError("duplicate key at leaf depth")
        split = self._split(items, depth)
        return self.hasher.hash_node(
            self._subtree_hash(items[:split], depth + 1),
            self._subtree_hash(items[split:], depth + 1),
        )

    @staticmethod
    def _split(items, depth: int) -> int:
        """First index whose key has bit `depth` set (items sorted, so the
        bit partitions them contiguously)."""
        lo, hi = 0, len(items)
        while lo < hi:
            mid = (lo + hi) // 2
            if bit_at(items[mid][0], depth):
                hi = mid
            else:
                lo = mid + 1
        return lo

    def prove(self, key: bytes) -> SmtProof:
        """Membership proof if `key` is present, else a non-membership
        proof (empty or foreign-collapsed terminal)."""
        items = sorted(self._leaves.items())
        bitmap = bytearray(32)
        siblings: list[bytes] = []
        depth = 0
        while True:
            if not items:
                return SmtProof(bytes(bitmap), siblings, ("empty", depth))
            if len(items) == 1:
                k, leaf = items[0]
                if k == key:
                    term = ("leaf",) if depth == DEPTH else ("collapsed", depth)
                    return SmtProof(bytes(bitmap), siblings, term)
                if depth == DEPTH:
                    raise SmtError("distinct keys cannot share all 256 bits")
                return SmtProof(bytes(bitmap), siblings, ("collapsed_other", depth, k, leaf))
            if depth == DEPTH:
                raise SmtError("duplicate key at leaf depth")
            split = self._split(items, depth)
            left, right = items[:split], items[split:]
            if bit_at(key, depth):
                sibling_items, items = left, right
            else:
                sibling_items, items = right, left
            if sibling_items:
                bitmap[depth >> 3] |= 0x80 >> (depth & 7)
                siblings.append(self._subtree_hash(sibling_items, depth + 1))
            depth += 1
