"""Binary merkle root (reference: crypto/merkle/src/lib.rs:13-52).

Leaves padded to a power of two; a present-left/absent-right pair hashes
with ZERO_HASH as the right sibling; fully absent pairs propagate absence.
Empty input -> ZERO_HASH; single leaf -> itself.
"""

from __future__ import annotations

from kaspa_tpu.crypto import hashing as h


def merkle_hash(left: bytes, right: bytes, hasher_factory=h.MerkleBranchHash) -> bytes:
    hasher = hasher_factory()
    hasher.update(left)
    hasher.update(right)
    return hasher.digest()


def calc_merkle_root(hashes: list, hasher_factory=h.MerkleBranchHash) -> bytes:
    if not hashes:
        return h.ZERO_HASH
    level = list(hashes)
    if len(level) == 1:
        return level[0]
    while len(level) > 1:
        nxt = []
        for i in range(0, len(level), 2):
            left = level[i]
            right = level[i + 1] if i + 1 < len(level) else None
            if left is None:
                nxt.append(None)
            else:
                nxt.append(merkle_hash(left, right if right is not None else h.ZERO_HASH, hasher_factory))
        level = nxt
    return level[0]


def calc_hash_merkle_root(txs) -> bytes:
    """Merkle root over tx hashes (consensus/core/src/merkle.rs)."""
    from kaspa_tpu.consensus import hashing as chash

    return calc_merkle_root([chash.tx_hash(tx) for tx in txs])


def calc_hash_merkle_root_pre_crescendo(txs) -> bytes:
    from kaspa_tpu.consensus import hashing as chash

    return calc_merkle_root([chash.tx_hash_pre_crescendo(tx) for tx in txs])


def calc_accepted_id_merkle_root_pre_crescendo(accepted_tx_ids: list) -> bytes:
    return calc_merkle_root(sorted(accepted_tx_ids))
