"""Vectorised ChaCha20 keystream (djb variant, 64-bit counter, nonce 0).

Matches rand_chacha's ChaCha20Rng::from_seed(key).fill_bytes(..) used for
muhash element expansion (crypto/muhash/src/lib.rs:152-168): keystream
blocks from counter 0 with stream id 0.  numpy-vectorised over a batch of
keys — this is the host-side element-generation throughput path feeding the
TPU U3072 reduction.
"""

from __future__ import annotations

import numpy as np

_CONSTANTS = np.array([0x61707865, 0x3320646E, 0x79622D32, 0x6B206574], dtype=np.uint32)


def _rotl(x, n):
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _quarter(s, a, b, c, d):
    s[a] += s[b]
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] += s[d]
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] += s[b]
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] += s[d]
    s[b] = _rotl(s[b] ^ s[c], 7)


def keystream(keys: np.ndarray, n_bytes: int) -> np.ndarray:
    """keys: [N, 32] uint8 -> [N, n_bytes] uint8 keystream (counter from 0)."""
    assert keys.ndim == 2 and keys.shape[1] == 32
    n = keys.shape[0]
    key_words = keys.view("<u4").reshape(n, 8).astype(np.uint32)
    n_blocks = (n_bytes + 63) // 64
    out = np.empty((n, n_blocks * 64), dtype=np.uint8)
    with np.errstate(over="ignore"):
        for blk in range(n_blocks):
            init = np.empty((16, n), dtype=np.uint32)
            init[0:4] = _CONSTANTS[:, None]
            init[4:12] = key_words.T
            init[12] = np.uint32(blk)  # 64-bit LE counter, low word
            init[13] = 0
            init[14] = 0  # nonce / stream id 0
            init[15] = 0
            s = init.copy()
            for _ in range(10):
                _quarter(s, 0, 4, 8, 12)
                _quarter(s, 1, 5, 9, 13)
                _quarter(s, 2, 6, 10, 14)
                _quarter(s, 3, 7, 11, 15)
                _quarter(s, 0, 5, 10, 15)
                _quarter(s, 1, 6, 11, 12)
                _quarter(s, 2, 7, 8, 13)
                _quarter(s, 3, 4, 9, 14)
            s += init
            out[:, blk * 64 : (blk + 1) * 64] = s.T.astype("<u4").view(np.uint8).reshape(n, 64)
    return out[:, :n_bytes]
