"""Vectorised ChaCha20 keystream (djb variant, 64-bit counter, nonce 0).

Matches rand_chacha's ChaCha20Rng::from_seed(key).fill_bytes(..) used for
muhash element expansion (crypto/muhash/src/lib.rs:152-168): keystream
blocks from counter 0 with stream id 0.  numpy-vectorised over a batch of
keys — this is the host-side element-generation throughput path feeding the
TPU U3072 reduction.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from kaspa_tpu.utils.sync import ranked_lock

import numpy as np

_CONSTANTS = np.array([0x61707865, 0x3320646E, 0x79622D32, 0x6B206574], dtype=np.uint32)

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "native", "hostcrypto", "hostcrypto.cc")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "..", "..", "native", "hostcrypto", "libhostcrypto.so")
_LOCK = ranked_lock("chacha.build")
_LIB = None
_LIB_FAILED = False


def _native_lib():
    """Build/load the native keystream library; None if unavailable."""
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        try:
            if not os.path.exists(_LIB_PATH) or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC):
                # atomic temp+rename so concurrent processes never load a
                # half-written .so
                tmp = _LIB_PATH + f".tmp{os.getpid()}"
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp, _LIB_PATH)
            lib = ctypes.CDLL(_LIB_PATH)
            lib.chacha20_keystream_batch.argtypes = [
                ctypes.c_char_p,
                ctypes.c_uint64,
                ctypes.c_void_p,
                ctypes.c_uint64,
            ]
            _LIB = lib
        except Exception:
            _LIB_FAILED = True
    return _LIB


def _rotl(x, n):
    return (x << np.uint32(n)) | (x >> np.uint32(32 - n))


def _quarter(s, a, b, c, d):
    s[a] += s[b]
    s[d] = _rotl(s[d] ^ s[a], 16)
    s[c] += s[d]
    s[b] = _rotl(s[b] ^ s[c], 12)
    s[a] += s[b]
    s[d] = _rotl(s[d] ^ s[a], 8)
    s[c] += s[d]
    s[b] = _rotl(s[b] ^ s[c], 7)


def keystream(keys: np.ndarray, n_bytes: int) -> np.ndarray:
    """keys: [N, 32] uint8 -> [N, n_bytes] uint8 keystream (counter from 0).

    Uses the native C path when available (the per-element host hot loop of
    muhash element expansion); falls back to the vectorised numpy rounds.
    """
    assert keys.ndim == 2 and keys.shape[1] == 32
    n = keys.shape[0]
    lib = _native_lib()
    if lib is not None and n > 0:
        keys_u8 = np.ascontiguousarray(keys, dtype=np.uint8)
        out = np.empty((n, n_bytes), dtype=np.uint8)
        lib.chacha20_keystream_batch(
            keys_u8.ctypes.data_as(ctypes.c_char_p), n, out.ctypes.data_as(ctypes.c_void_p), n_bytes
        )
        return out
    key_words = keys.view("<u4").reshape(n, 8).astype(np.uint32)
    n_blocks = (n_bytes + 63) // 64
    out = np.empty((n, n_blocks * 64), dtype=np.uint8)
    with np.errstate(over="ignore"):
        for blk in range(n_blocks):
            init = np.empty((16, n), dtype=np.uint32)
            init[0:4] = _CONSTANTS[:, None]
            init[4:12] = key_words.T
            init[12] = np.uint32(blk)  # 64-bit LE counter, low word
            init[13] = 0
            init[14] = 0  # nonce / stream id 0
            init[15] = 0
            s = init.copy()
            for _ in range(10):
                _quarter(s, 0, 4, 8, 12)
                _quarter(s, 1, 5, 9, 13)
                _quarter(s, 2, 6, 10, 14)
                _quarter(s, 3, 7, 11, 15)
                _quarter(s, 0, 5, 10, 15)
                _quarter(s, 1, 6, 11, 12)
                _quarter(s, 2, 7, 8, 13)
                _quarter(s, 3, 4, 9, 14)
            s += init
            out[:, blk * 64 : (blk + 1) * 64] = (
                np.ascontiguousarray(s.T, dtype="<u4").view(np.uint8).reshape(n, 64)
            )
    return out[:, :n_bytes]
