"""BN254 (alt_bn128) pairing curve: fields, groups, optimal-ate pairing.

Host-side exact arithmetic backing the Groth16 ZK precompile
(reference: crypto/txscript/src/zk_precompiles/groth16/mod.rs, which
delegates to arkworks ark-bn254).  Python integers give exact field math;
the tower is the standard one:

    Fq2  = Fq[u]  / (u^2 + 1)
    Fq6  = Fq2[v] / (v^3 - xi),  xi = 9 + u
    Fq12 = Fq6[w] / (w^2 - v)

Serialization matches ark-serialize compressed mode bit-for-bit:
little-endian base-field limbs with SW flags in the two most significant
bits of the final byte (bit 7: y-is-negative, bit 6: point-at-infinity);
G2/Fq2 x-coordinates serialize c0 || c1 with flags on c1's top byte.

Consensus scripts budget ~10ms per verification on the reference; this
implementation is exact rather than fast — the precompile is metered, so
throughput is bounded by script-units, not by this code.
"""

from __future__ import annotations

# Base field and scalar field moduli
P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
R = 21888242871839275222246405745257275088548364400416034343698204186575808495617

# BN parameter x: p(x), r(x) per Barreto-Naehrig; 6x+2 drives the Miller loop
BN_X = 4965661367192848881
ATE_LOOP_COUNT = 6 * BN_X + 2  # 29793968203157093288


# ----------------------------------------------------------------------
# field towers (elements are ints / tuples of ints; functions are pure)
# ----------------------------------------------------------------------


def f1_inv(a: int) -> int:
    return pow(a, -1, P)


# Fq2: (c0, c1) = c0 + c1*u, u^2 = -1
def f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def f2_neg(a):
    return (-a[0] % P, -a[1] % P)


def f2_mul(a, b):
    t0 = a[0] * b[0] % P
    t1 = a[1] * b[1] % P
    return ((t0 - t1) % P, ((a[0] + a[1]) * (b[0] + b[1]) - t0 - t1) % P)


def f2_sqr(a):
    # (c0+c1 u)^2 = (c0+c1)(c0-c1) + 2 c0 c1 u
    return ((a[0] + a[1]) * (a[0] - a[1]) % P, 2 * a[0] * a[1] % P)


def f2_scalar(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def f2_inv(a):
    norm = (a[0] * a[0] + a[1] * a[1]) % P
    ninv = f1_inv(norm)
    return (a[0] * ninv % P, -a[1] * ninv % P)


def f2_conj(a):
    return (a[0], -a[1] % P)


F2_ZERO = (0, 0)
F2_ONE = (1, 0)
XI = (9, 1)  # the Fq6 non-residue


# Fq6: (a0, a1, a2) over Fq2, v^3 = XI
def f6_add(a, b):
    return (f2_add(a[0], b[0]), f2_add(a[1], b[1]), f2_add(a[2], b[2]))


def f6_sub(a, b):
    return (f2_sub(a[0], b[0]), f2_sub(a[1], b[1]), f2_sub(a[2], b[2]))


def f6_neg(a):
    return (f2_neg(a[0]), f2_neg(a[1]), f2_neg(a[2]))


def f6_mul(a, b):
    v0 = f2_mul(a[0], b[0])
    v1 = f2_mul(a[1], b[1])
    v2 = f2_mul(a[2], b[2])
    c0 = f2_add(v0, f2_mul(XI, f2_sub(f2_mul(f2_add(a[1], a[2]), f2_add(b[1], b[2])), f2_add(v1, v2))))
    c1 = f2_add(f2_sub(f2_mul(f2_add(a[0], a[1]), f2_add(b[0], b[1])), f2_add(v0, v1)), f2_mul(XI, v2))
    c2 = f2_add(f2_sub(f2_mul(f2_add(a[0], a[2]), f2_add(b[0], b[2])), f2_add(v0, v2)), v1)
    return (c0, c1, c2)


def f6_sqr(a):
    return f6_mul(a, a)


def f6_mul_by_xi(a):
    # multiply by v: (a0,a1,a2) -> (xi*a2, a0, a1)
    return (f2_mul(XI, a[2]), a[0], a[1])


def f6_inv(a):
    c0 = f2_sub(f2_sqr(a[0]), f2_mul(XI, f2_mul(a[1], a[2])))
    c1 = f2_sub(f2_mul(XI, f2_sqr(a[2])), f2_mul(a[0], a[1]))
    c2 = f2_sub(f2_sqr(a[1]), f2_mul(a[0], a[2]))
    t = f2_inv(
        f2_add(f2_mul(a[0], c0), f2_mul(XI, f2_add(f2_mul(a[2], c1), f2_mul(a[1], c2))))
    )
    return (f2_mul(c0, t), f2_mul(c1, t), f2_mul(c2, t))


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


# Fq12: (a0, a1) over Fq6, w^2 = v
def f12_mul(a, b):
    v0 = f6_mul(a[0], b[0])
    v1 = f6_mul(a[1], b[1])
    return (
        f6_add(v0, f6_mul_by_xi(v1)),
        f6_sub(f6_sub(f6_mul(f6_add(a[0], a[1]), f6_add(b[0], b[1])), v0), v1),
    )


def f12_sqr(a):
    return f12_mul(a, a)


def f12_conj(a):
    return (a[0], f6_neg(a[1]))


def f12_inv(a):
    t = f6_inv(f6_sub(f6_sqr(a[0]), f6_mul_by_xi(f6_sqr(a[1]))))
    return (f6_mul(a[0], t), f6_neg(f6_mul(a[1], t)))


def f12_pow(a, e: int):
    result = F12_ONE
    base = a
    while e:
        if e & 1:
            result = f12_mul(result, base)
        base = f12_sqr(base)
        e >>= 1
    return result


F12_ONE = (F6_ONE, F6_ZERO)


# Frobenius coefficients: gamma_1[i] = xi^((p-1)*i/6) in Fq2
def _frob_coeffs():
    exp = (P - 1) // 6
    c = []
    for i in range(6):
        # xi^(exp*i) computed in Fq2
        acc = F2_ONE
        base = XI
        e = exp * i
        while e:
            if e & 1:
                acc = f2_mul(acc, base)
            base = f2_sqr(base)
            e >>= 1
        c.append(acc)
    return c


_G1COEF = _frob_coeffs()


def f2_frob(a):
    return f2_conj(a)


def f6_frob(a):
    return (
        f2_conj(a[0]),
        f2_mul(f2_conj(a[1]), _G1COEF[2]),
        f2_mul(f2_conj(a[2]), _G1COEF[4]),
    )


def f12_frob(a):
    # (b0 + b1 w)^p = frob6(b0) + frob6(b1) * w^(p-1) * w, with
    # w^(p-1) = xi^((p-1)/6) an Fq2 scalar applied to every coefficient
    c0 = f6_frob(a[0])
    t = f6_frob(a[1])
    c1 = tuple(f2_mul(ti, _G1COEF[1]) for ti in t)
    return (c0, c1)


# ----------------------------------------------------------------------
# groups (affine tuples; None = infinity)
# ----------------------------------------------------------------------

G1_GEN = (1, 2)
G2_GEN = (
    (
        10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634,
    ),
    (
        8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531,
    ),
)

B1 = 3
# b2 = 3 / xi
B2 = f2_mul((3, 0), f2_inv(XI))


def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - x * x * x - B1) % P == 0


def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return f2_sub(f2_sqr(y), f2_add(f2_mul(x, f2_sqr(x)), B2)) == F2_ZERO


def g1_add(a, b):
    if a is None:
        return b
    if b is None:
        return a
    if a[0] == b[0]:
        if (a[1] + b[1]) % P == 0:
            return None
        lam = (3 * a[0] * a[0]) * f1_inv(2 * a[1]) % P
    else:
        lam = (b[1] - a[1]) * f1_inv(b[0] - a[0]) % P
    x = (lam * lam - a[0] - b[0]) % P
    return (x, (lam * (a[0] - x) - a[1]) % P)


def g1_neg(a):
    return None if a is None else (a[0], -a[1] % P)


def g1_mul(a, k: int):
    k %= R
    result = None
    addend = a
    while k:
        if k & 1:
            result = g1_add(result, addend)
        addend = g1_add(addend, addend)
        k >>= 1
    return result


def g2_add(a, b):
    if a is None:
        return b
    if b is None:
        return a
    if a[0] == b[0]:
        if f2_add(a[1], b[1]) == F2_ZERO:
            return None
        lam = f2_mul(f2_scalar(f2_sqr(a[0]), 3), f2_inv(f2_scalar(a[1], 2)))
    else:
        lam = f2_mul(f2_sub(b[1], a[1]), f2_inv(f2_sub(b[0], a[0])))
    x = f2_sub(f2_sub(f2_sqr(lam), a[0]), b[0])
    return (x, f2_sub(f2_mul(lam, f2_sub(a[0], x)), a[1]))


def g2_neg(a):
    return None if a is None else (a[0], f2_neg(a[1]))


def g2_mul(a, k: int):
    k %= R
    result = None
    addend = a
    while k:
        if k & 1:
            result = g2_add(result, addend)
        addend = g2_add(addend, addend)
        k >>= 1
    return result


def g2_frobenius(pt):
    """pi(x, y) = (x^p * gamma_1_2, y^p * gamma_1_3) — the untwist-Frobenius-
    twist endomorphism on the twisted curve."""
    if pt is None:
        return None
    x, y = pt
    return (f2_mul(f2_conj(x), _G12), f2_mul(f2_conj(y), _G13))


# gamma coefficients for the twist Frobenius: xi^((p-1)/3), xi^((p-1)/2)
def _f2_pow(a, e):
    acc = F2_ONE
    base = a
    while e:
        if e & 1:
            acc = f2_mul(acc, base)
        base = f2_sqr(base)
        e >>= 1
    return acc


_G12 = _f2_pow(XI, (P - 1) // 3)
_G13 = _f2_pow(XI, (P - 1) // 2)


def g2_in_subgroup(pt) -> bool:
    """G2 subgroup membership: psi(P) == [6x^2]P (Scott's criterion for BN
    curves) — equivalent to (and much faster than) [r]P == O."""
    if pt is None:
        return True
    if not g2_is_on_curve(pt):
        return False
    return g2_frobenius(pt) == g2_mul(pt, 6 * BN_X * BN_X)


# ----------------------------------------------------------------------
# optimal ate pairing
# ----------------------------------------------------------------------


# Twist embedding: map G2 (on E'/Fq2) into E(Fq12):
#   (x, y) -> (x * w^2, y * w^3)
# where w^2 = v (Fq6 basis) — x*w^2 has Fq6 coords (0, x, 0) at position 0,
# y*w^3 = y*v*w has Fq6 coords (0, y, 0) at position 1.


def _twist(pt):
    if pt is None:
        return None
    x, y = pt
    return ((F2_ZERO, x, F2_ZERO), F6_ZERO), ((F2_ZERO, y, F2_ZERO),)


def _f12_from_f2_at(c, six_pos: int, w_pos: int):
    f6 = [F2_ZERO, F2_ZERO, F2_ZERO]
    f6[six_pos] = c
    f6 = tuple(f6)
    return (f6, F6_ZERO) if w_pos == 0 else (F6_ZERO, f6)


def _embed_g2(pt):
    """G2 point -> coordinates in Fq12 via the twist map."""
    x, y = pt
    return (_f12_from_f2_at(x, 1, 0), _f12_from_f2_at(y, 1, 1))


def _embed_g1(pt):
    x, y = pt
    return (((x, 0), F2_ZERO, F2_ZERO), F6_ZERO), (((y, 0), F2_ZERO, F2_ZERO), F6_ZERO)


def f12_sub(a, b):
    return (f6_sub(a[0], b[0]), f6_sub(a[1], b[1]))


def f12_add(a, b):
    return (f6_add(a[0], b[0]), f6_add(a[1], b[1]))


def f12_scalarF6(a, s):
    return (f6_mul(a[0], s[0] if False else s), f6_mul(a[1], s))


def _is_zero12(a):
    return a == (F6_ZERO, F6_ZERO)


def _line_eval(q1, q2, p):
    """Line through embedded points q1, q2 evaluated at embedded p (all in
    E(Fq12) affine coords).  Returns the Fq12 line value."""
    x1, y1 = q1
    x2, y2 = q2
    xp, yp = p
    if x1 == x2:
        if f12_add(y1, y2) == (F6_ZERO, F6_ZERO):
            # vertical: x_p - x1
            return f12_sub(xp, x1)
        lam = f12_mul(
            f12_scalar_int(f12_sqr(x1), 3), f12_inv(f12_scalar_int(y1, 2))
        )
    else:
        lam = f12_mul(f12_sub(y2, y1), f12_inv(f12_sub(x2, x1)))
    # l(P) = (y_p - y1) - lam (x_p - x1)
    return f12_sub(f12_sub(yp, y1), f12_mul(lam, f12_sub(xp, x1)))


def f12_scalar_int(a, k: int):
    return (
        tuple(f2_scalar(c, k) for c in a[0]),
        tuple(f2_scalar(c, k) for c in a[1]),
    )


def miller_loop(q, p):
    """Optimal ate Miller loop f_{6x+2,Q}(P) * l_{[6x+2]Q,piQ}(P) *
    l_{[6x+2]Q+piQ, -pi2Q}(P) for q in G2, p in G1 (affine, not infinity)."""
    if q is None or p is None:
        return F12_ONE
    eq = _embed_g2(q)
    ep = _embed_g1(p)
    t = q  # running point on the twist (cheaper arithmetic)
    f = F12_ONE
    for bit in bin(ATE_LOOP_COUNT)[3:]:
        f = f12_mul(f12_sqr(f), _line_eval(_embed_g2(t), _embed_g2(t), ep))
        t = g2_add(t, t)
        if bit == "1":
            f = f12_mul(f, _line_eval(_embed_g2(t), eq, ep))
            t = g2_add(t, q)
    # the two final lines with Frobenius images
    q1 = g2_frobenius(q)
    q2 = g2_neg(g2_frobenius(q1))
    f = f12_mul(f, _line_eval(_embed_g2(t), _embed_g2(q1), ep))
    t = g2_add(t, q1)
    f = f12_mul(f, _line_eval(_embed_g2(t), _embed_g2(q2), ep))
    return f


def final_exponentiation(f):
    """f^((p^12-1)/r): easy part (p^6-1)(p^2+1) then hard part by plain
    exponentiation of the cofactor (exact, if not the fastest route)."""
    # easy part
    f1 = f12_conj(f)  # f^(p^6)
    f2i = f12_inv(f)
    f = f12_mul(f1, f2i)  # f^(p^6 - 1)
    f = f12_mul(f12_frob(f12_frob(f)), f)  # ^(p^2+1)
    # hard part: (p^4 - p^2 + 1)/r
    e = (P**4 - P**2 + 1) // R
    return f12_pow(f, e)


def pairing(q, p):
    """e(p, q) for p in G1, q in G2 (note the conventional argument order
    e: G1 x G2 -> GT)."""
    if p is None or q is None:
        return F12_ONE
    return final_exponentiation(miller_loop(q, p))


def multi_pairing(pairs) -> bool:
    """prod e(p_i, q_i) == 1?  One shared final exponentiation."""
    f = F12_ONE
    for p, q in pairs:
        if p is None or q is None:
            continue
        f = f12_mul(f, miller_loop(q, p))
    return final_exponentiation(f) == F12_ONE


# ----------------------------------------------------------------------
# ark-serialize compressed encoding
# ----------------------------------------------------------------------

FLAG_INF = 1 << 6
FLAG_NEG = 1 << 7


class DeserializeError(Exception):
    pass


def _fq_from_le(b: bytes) -> int:
    v = int.from_bytes(b, "little")
    if v >= P:
        raise DeserializeError("base field element not canonical")
    return v


def _fq2_gt_half(y) -> bool:
    """arkworks 'is negative': y > -y under the Fq2 lexicographic order
    (c1 first, then c0)."""
    ny = f2_neg(y)
    return (y[1], y[0]) > (ny[1], ny[0])


def _fq_gt_half(y: int) -> bool:
    return y > P - y


def g1_deserialize_compressed(b: bytes, validate: bool = True):
    if len(b) != 32:
        raise DeserializeError(f"invalid G1 length {len(b)}")
    flags = b[31] & 0xC0
    data = bytes(b[:31]) + bytes([b[31] & 0x3F])
    if flags & FLAG_INF:
        if any(data):
            raise DeserializeError("non-zero infinity encoding")
        return None
    x = _fq_from_le(data)
    rhs = (x * x * x + B1) % P
    y = pow(rhs, (P + 1) // 4, P)
    if y * y % P != rhs:
        raise DeserializeError("x not on curve")
    if bool(flags & FLAG_NEG) != _fq_gt_half(y):
        y = P - y
    pt = (x, y)
    if validate and not g1_is_on_curve(pt):
        raise DeserializeError("G1 point not on curve")
    return pt


def g1_serialize_compressed(pt) -> bytes:
    if pt is None:
        return b"\x00" * 31 + bytes([FLAG_INF])
    x, y = pt
    b = bytearray(x.to_bytes(32, "little"))
    if _fq_gt_half(y):
        b[31] |= FLAG_NEG
    return bytes(b)


def _f2_sqrt(a):
    """Square root in Fq2 (p = 3 mod 4 route via the norm)."""
    if a == F2_ZERO:
        return F2_ZERO
    # Tonelli-like: candidate = a^((q+7)/16)? For Fq2 with q = p^2,
    # q = 1 mod 4 — use the complex method: sqrt(a) via norm.
    c0, c1 = a
    if c1 == 0:
        # sqrt of base-field element inside Fq2
        s = pow(c0, (P + 1) // 4, P)
        if s * s % P == c0:
            return (s, 0)
        # sqrt(c0) = s'*u with s'^2 = -c0
        s = pow((-c0) % P, (P + 1) // 4, P)
        if s * s % P == (-c0) % P:
            return (0, s)
        return None
    # norm = c0^2 + c1^2; alpha = sqrt(norm) in Fq
    norm = (c0 * c0 + c1 * c1) % P
    alpha = pow(norm, (P + 1) // 4, P)
    if alpha * alpha % P != norm:
        return None
    # delta = (c0 + alpha)/2
    inv2 = f1_inv(2)
    delta = (c0 + alpha) * inv2 % P
    x0 = pow(delta, (P + 1) // 4, P)
    if x0 * x0 % P != delta:
        delta = (c0 - alpha) * inv2 % P
        x0 = pow(delta, (P + 1) // 4, P)
        if x0 * x0 % P != delta:
            return None
    x1 = c1 * inv2 % P * f1_inv(x0) % P
    cand = (x0, x1)
    return cand if f2_sqr(cand) == a else None


def g2_deserialize_compressed(b: bytes, validate: bool = True):
    if len(b) != 64:
        raise DeserializeError(f"invalid G2 length {len(b)}")
    flags = b[63] & 0xC0
    c0 = _fq_from_le(b[:32])
    data1 = bytes(b[32:63]) + bytes([b[63] & 0x3F])
    c1 = _fq_from_le(data1)
    if flags & FLAG_INF:
        if c0 or c1:
            raise DeserializeError("non-zero infinity encoding")
        return None
    x = (c0, c1)
    rhs = f2_add(f2_mul(x, f2_sqr(x)), B2)
    y = _f2_sqrt(rhs)
    if y is None:
        raise DeserializeError("x not on twist curve")
    if bool(flags & FLAG_NEG) != _fq2_gt_half(y):
        y = f2_neg(y)
    pt = (x, y)
    if validate and not g2_in_subgroup(pt):
        raise DeserializeError("G2 point not in subgroup")
    return pt


def g2_serialize_compressed(pt) -> bytes:
    if pt is None:
        return b"\x00" * 63 + bytes([FLAG_INF])
    x, y = pt
    b = bytearray(x[0].to_bytes(32, "little") + x[1].to_bytes(32, "little"))
    if _fq2_gt_half(y):
        b[63] |= FLAG_NEG
    return bytes(b)


def fr_deserialize(b: bytes) -> int:
    """ark Fr 'uncompressed' canonical: 32 LE bytes, must be < r."""
    if len(b) != 32:
        raise DeserializeError(f"Invalid Fr length {len(b)}")
    v = int.from_bytes(b, "little")
    if v >= R:
        raise DeserializeError("scalar not canonical")
    return v


def fr_serialize(v: int) -> bytes:
    return (v % R).to_bytes(32, "little")
