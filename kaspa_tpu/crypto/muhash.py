"""MuHash: homomorphic multiset hash for UTXO commitments.

Re-implementation of the reference's kaspa-muhash (crypto/muhash/src/lib.rs,
u3072.rs) + the consensus extensions (consensus/core/src/muhash.rs):

- element = Blake2b("MuHashElement") -> ChaCha20 keystream (384 bytes) ->
  3072-bit little-endian integer in GF(2**3072 - 1103717)
- add = numerator *= elem; remove = denominator *= elem; combine = pairwise
- finalize = normalize (denominator inverse) -> 384-byte LE ->
  Blake2b("MuHashFinalize")

The host object keeps exact python-int accumulators (cheap at 3072 bits);
bulk diffs route through the TPU tree-product kernel (ops/muhash_ops.py)
whose result combines into the accumulator with one multiply.
"""

from __future__ import annotations

import numpy as np

from kaspa_tpu.crypto import chacha
from kaspa_tpu.crypto import hashing as h

ELEMENT_BYTE_SIZE = 384
PRIME = 2**3072 - 1103717  # u3072.rs:22


def element_hashes_to_ints(hashes: np.ndarray) -> list[int]:
    """[N, 32] uint8 element hashes -> N field elements (vectorised chacha)."""
    ks = chacha.keystream(hashes, ELEMENT_BYTE_SIZE)
    return [int.from_bytes(ks[i].tobytes(), "little") % PRIME for i in range(ks.shape[0])]


def data_to_element(data: bytes) -> int:
    hasher = h.MuHashElementHash()
    hasher.update(data)
    digest = np.frombuffer(hasher.digest(), dtype=np.uint8).reshape(1, 32)
    return element_hashes_to_ints(digest)[0]


def serialize_utxo(outpoint, entry) -> bytes:
    """Element preimage for a UTXO (consensus/core/src/muhash.rs write_utxo)."""
    out = bytearray()
    out += outpoint.transaction_id
    out += outpoint.index.to_bytes(4, "little")
    out += entry.block_daa_score.to_bytes(8, "little")
    out += entry.amount.to_bytes(8, "little")
    out += b"\x01" if entry.is_coinbase else b"\x00"
    out += entry.script_public_key.version.to_bytes(2, "little")
    out += len(entry.script_public_key.script).to_bytes(8, "little")
    out += entry.script_public_key.script
    if entry.covenant_id is not None:
        out += entry.covenant_id
    return bytes(out)


class MuHash:
    __slots__ = ("numerator", "denominator")

    def __init__(self, numerator: int = 1, denominator: int = 1):
        self.numerator = numerator
        self.denominator = denominator

    def add_element(self, data: bytes) -> None:
        self.numerator = self.numerator * data_to_element(data) % PRIME

    def remove_element(self, data: bytes) -> None:
        self.denominator = self.denominator * data_to_element(data) % PRIME

    def combine(self, other: "MuHash") -> None:
        self.numerator = self.numerator * other.numerator % PRIME
        self.denominator = self.denominator * other.denominator % PRIME

    def normalize(self) -> None:
        if self.denominator != 1:
            self.numerator = self.numerator * pow(self.denominator, -1, PRIME) % PRIME
            self.denominator = 1

    def serialize(self) -> bytes:
        self.normalize()
        return self.numerator.to_bytes(ELEMENT_BYTE_SIZE, "little")

    @staticmethod
    def deserialize(data: bytes) -> "MuHash":
        assert len(data) == ELEMENT_BYTE_SIZE
        v = int.from_bytes(data, "little")
        if v >= PRIME:
            raise OverflowError("Overflow in the MuHash field")
        return MuHash(v)

    def finalize(self) -> bytes:
        hasher = h.MuHashFinalizeHash()
        hasher.update(self.serialize())
        return hasher.digest()

    def clone(self) -> "MuHash":
        return MuHash(self.numerator, self.denominator)

    # --- consensus extensions (consensus/core/src/muhash.rs) ---

    def add_utxo(self, outpoint, entry) -> None:
        self.add_element(serialize_utxo(outpoint, entry))

    def remove_utxo(self, outpoint, entry) -> None:
        self.remove_element(serialize_utxo(outpoint, entry))

    def add_transaction(self, tx, utxo_entries, block_daa_score: int) -> None:
        """Remove spent entries, add created outputs (muhash.rs:16-34)."""
        from kaspa_tpu.consensus.model import TransactionOutpoint, UtxoEntry

        tx_id = tx.id()
        for inp, entry in zip(tx.inputs, utxo_entries):
            self.remove_element(serialize_utxo(inp.previous_outpoint, entry))
        for i, output in enumerate(tx.outputs):
            outpoint = TransactionOutpoint(tx_id, i)
            entry = UtxoEntry(
                output.value,
                output.script_public_key,
                block_daa_score,
                tx.is_coinbase(),
                output.covenant.covenant_id if output.covenant is not None else None,
            )
            self.add_element(serialize_utxo(outpoint, entry))


EMPTY_MUHASH = MuHash().finalize()
