"""MuHash: homomorphic multiset hash for UTXO commitments.

Re-implementation of the reference's kaspa-muhash (crypto/muhash/src/lib.rs,
u3072.rs) + the consensus extensions (consensus/core/src/muhash.rs):

- element = Blake2b("MuHashElement") -> ChaCha20 keystream (384 bytes) ->
  3072-bit little-endian integer in GF(2**3072 - 1103717)
- add = numerator *= elem; remove = denominator *= elem; combine = pairwise
- finalize = normalize (denominator inverse) -> 384-byte LE ->
  Blake2b("MuHashFinalize")

The host object keeps exact python-int accumulators (cheap at 3072 bits).
Bulk diffs — ``add_transactions_batch``, the call the consensus virtual
processor makes per mergeset — derive all element preimages at once
(native-vectorised ChaCha20) and, above ``DEVICE_BATCH_THRESHOLD``
elements, reduce the products through the device U3072 tree-product kernel
(ops/muhash_ops.batch_product_ints); the two bulk products (numerator /
denominator) each combine into the accumulator with one host multiply.
"""

from __future__ import annotations

import numpy as np

from kaspa_tpu.crypto import chacha
from kaspa_tpu.crypto import hashing as h
from kaspa_tpu.observability import trace

ELEMENT_BYTE_SIZE = 384
PRIME = 2**3072 - 1103717  # u3072.rs:22


def element_hashes_to_ints(hashes: np.ndarray) -> list[int]:
    """[N, 32] uint8 element hashes -> N field elements (vectorised chacha)."""
    ks = chacha.keystream(hashes, ELEMENT_BYTE_SIZE)
    return [int.from_bytes(ks[i].tobytes(), "little") % PRIME for i in range(ks.shape[0])]


def data_to_element(data: bytes) -> int:
    return element_hashes_to_ints(_digests([data]))[0]


def _digests(preimages: list[bytes]) -> np.ndarray:
    """[N, 32] uint8 MuHashElement digests of the preimages."""
    out = np.empty((len(preimages), 32), dtype=np.uint8)
    for i, p in enumerate(preimages):
        hasher = h.MuHashElementHash()
        hasher.update(p)
        out[i] = np.frombuffer(hasher.digest(), dtype=np.uint8)
    return out


# Bulk products with at least this many elements go through the device
# tree-product kernel; smaller ones multiply on host (dispatch overhead of a
# padded 64-wide bucket isn't worth it below this).
DEVICE_BATCH_THRESHOLD = 32


def elements_from_preimages(preimages: list[bytes]) -> list[int]:
    """Batch preimage -> field-element derivation (vectorised keystream)."""
    if not preimages:
        return []
    return element_hashes_to_ints(_digests(preimages))


def bulk_element_product(preimages: list[bytes], use_device: bool = True) -> int:
    """Product of the field elements of `preimages` mod PRIME.

    Routes through the device tree-product kernel above the threshold.  The
    device path views the raw keystream bytes as 16-bit limbs directly —
    values in [PRIME, 2**3072) are legal lazy-limb inputs that the kernel's
    final canon reduces — so no per-element host bigint conversion happens."""
    if not preimages:
        return 1
    if use_device and len(preimages) >= DEVICE_BATCH_THRESHOLD:
        from kaspa_tpu.ops import muhash_ops

        ks = chacha.keystream(_digests(preimages), ELEMENT_BYTE_SIZE)
        limbs = ks.view(np.dtype("<u2")).astype(np.int32)  # [N, 192]
        return muhash_ops.batch_product_device(limbs)
    acc = 1
    for e in elements_from_preimages(preimages):
        acc = acc * e % PRIME
    return acc


def serialize_utxo(outpoint, entry) -> bytes:
    """Element preimage for a UTXO (consensus/core/src/muhash.rs write_utxo)."""
    out = bytearray()
    out += outpoint.transaction_id
    out += outpoint.index.to_bytes(4, "little")
    out += entry.block_daa_score.to_bytes(8, "little")
    out += entry.amount.to_bytes(8, "little")
    out += b"\x01" if entry.is_coinbase else b"\x00"
    out += entry.script_public_key.version.to_bytes(2, "little")
    out += len(entry.script_public_key.script).to_bytes(8, "little")
    out += entry.script_public_key.script
    if entry.covenant_id is not None:
        out += entry.covenant_id
    return bytes(out)


class MuHash:
    __slots__ = ("numerator", "denominator")

    def __init__(self, numerator: int = 1, denominator: int = 1):
        self.numerator = numerator
        self.denominator = denominator

    def add_element(self, data: bytes) -> None:
        self.numerator = self.numerator * data_to_element(data) % PRIME

    def remove_element(self, data: bytes) -> None:
        self.denominator = self.denominator * data_to_element(data) % PRIME

    def combine(self, other: "MuHash") -> None:
        self.numerator = self.numerator * other.numerator % PRIME
        self.denominator = self.denominator * other.denominator % PRIME

    def normalize(self) -> None:
        if self.denominator != 1:
            self.numerator = self.numerator * pow(self.denominator, -1, PRIME) % PRIME
            self.denominator = 1

    def serialize(self) -> bytes:
        self.normalize()
        return self.numerator.to_bytes(ELEMENT_BYTE_SIZE, "little")

    @staticmethod
    def deserialize(data: bytes) -> "MuHash":
        assert len(data) == ELEMENT_BYTE_SIZE
        v = int.from_bytes(data, "little")
        if v >= PRIME:
            raise OverflowError("Overflow in the MuHash field")
        return MuHash(v)

    def finalize(self) -> bytes:
        hasher = h.MuHashFinalizeHash()
        hasher.update(self.serialize())
        return hasher.digest()

    def clone(self) -> "MuHash":
        return MuHash(self.numerator, self.denominator)

    # --- consensus extensions (consensus/core/src/muhash.rs) ---

    def add_utxo(self, outpoint, entry) -> None:
        self.add_element(serialize_utxo(outpoint, entry))

    def remove_utxo(self, outpoint, entry) -> None:
        self.remove_element(serialize_utxo(outpoint, entry))

    def add_transaction(self, tx, utxo_entries, block_daa_score: int) -> None:
        """Remove spent entries, add created outputs (muhash.rs:16-34)."""
        adds, removes = _tx_element_preimages(tx, utxo_entries, block_daa_score)
        for p in removes:
            self.remove_element(p)
        for p in adds:
            self.add_element(p)

    def add_transactions_batch(self, items, use_device: bool = True) -> None:
        """Bulk `add_transaction` over ``[(tx, utxo_entries, daa_score)]``.

        All element preimages of the batch are derived together and the two
        monoid products (created outputs -> numerator, spent entries ->
        denominator) reduce through the device kernel above the threshold.
        Equivalent to calling add_transaction per item, in any order — the
        multiset hash is commutative (reference rayon map-reduce:
        consensus/src/pipeline/virtual_processor/utxo_validation.rs:334-363).
        """
        with trace.span("muhash.commit", txs=len(items)):
            adds: list[bytes] = []
            removes: list[bytes] = []
            for tx, entries, daa in items:
                a, r = _tx_element_preimages(tx, entries, daa)
                adds += a
                removes += r
            if adds:
                self.numerator = self.numerator * bulk_element_product(adds, use_device) % PRIME
            if removes:
                self.denominator = self.denominator * bulk_element_product(removes, use_device) % PRIME


def _tx_element_preimages(tx, utxo_entries, block_daa_score: int):
    """(added_preimages, removed_preimages) for one populated transaction."""
    from kaspa_tpu.consensus.model import TransactionOutpoint, UtxoEntry

    tx_id = tx.id()
    removes = [serialize_utxo(inp.previous_outpoint, entry) for inp, entry in zip(tx.inputs, utxo_entries)]
    adds = []
    for i, output in enumerate(tx.outputs):
        outpoint = TransactionOutpoint(tx_id, i)
        entry = UtxoEntry(
            output.value,
            output.script_public_key,
            block_daa_score,
            tx.is_coinbase(),
            output.covenant.covenant_id if output.covenant is not None else None,
        )
        adds.append(serialize_utxo(outpoint, entry))
    return adds, removes


EMPTY_MUHASH = MuHash().finalize()
