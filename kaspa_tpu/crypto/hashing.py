"""Domain-separated hashers (host side).

Mirrors the reference's hasher registry (crypto/hashes/src/hashers.rs:22-55):
- Blake2b-256 keyed by the domain string (blake2b_simd keyed mode ==
  hashlib.blake2b(key=..., digest_size=32)).
- SHA-256 prefixed once with SHA256(domain) (sha256_hasher macro).
- cSHAKE256-based PoW hashers live in kaspa_tpu/crypto/powhash.py.
- Blake3-keyed SeqCommit hashers (KIP-21) live in kaspa_tpu/crypto/blake3.py.

Hashes are plain 32-byte ``bytes``; hex display is the natural byte order
(crypto/hashes/src/lib.rs FromStr/Display).
"""

from __future__ import annotations

import hashlib

HASH_SIZE = 32
ZERO_HASH = b"\x00" * HASH_SIZE


def _blake2b_domain(domain: bytes):
    def new():
        return hashlib.blake2b(key=domain, digest_size=HASH_SIZE)

    return new


TransactionHash = _blake2b_domain(b"TransactionHash")
TransactionID = _blake2b_domain(b"TransactionID")
TransactionSigningHash = _blake2b_domain(b"TransactionSigningHash")
BlockHash = _blake2b_domain(b"BlockHash")
MerkleBranchHash = _blake2b_domain(b"MerkleBranchHash")
MuHashElementHash = _blake2b_domain(b"MuHashElement")
MuHashFinalizeHash = _blake2b_domain(b"MuHashFinalize")
PersonalMessageSigningHash = _blake2b_domain(b"PersonalMessageSigningHash")
CovenantID = _blake2b_domain(b"CovenantID")

_ECDSA_DOMAIN_HASH = hashlib.sha256(b"TransactionSigningHashECDSA").digest()


def TransactionSigningHashECDSA():
    """SHA256 prefixed with SHA256(domain) — hashers.rs sha256_hasher macro."""
    h = hashlib.sha256()
    h.update(_ECDSA_DOMAIN_HASH)
    return h


def hash_to_hex(h: bytes) -> str:
    return h.hex()


def hex_to_hash(s: str) -> bytes:
    b = bytes.fromhex(s)
    assert len(b) == HASH_SIZE
    return b


def hash_from_u64_word(word: int) -> bytes:
    """Hash::from_u64_word: the word occupies the highest little-endian u64."""
    return b"\x00" * 24 + word.to_bytes(8, "little")
