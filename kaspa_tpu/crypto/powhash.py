"""Proof-of-work hashing: cSHAKE256 PowHash + HeavyHash matrix.

Reference: crypto/hashes/src/pow_hashers.rs (cSHAKE256 with customization
strings "ProofOfWorkHash" / "HeavyHash", single keccak-f[1600] permutation
per hash since inputs fit one rate block) and consensus/pow/src/
{lib.rs,matrix.rs,xoshiro.rs} (the 64x64 nibble matrix, rank-checked,
xoshiro256++-seeded from the pre-PoW hash).

The keccak permutation is implemented from the FIPS-202 spec; the cSHAKE
prefix state is derived per NIST SP 800-185 (bytepad(encode_string("") ||
encode_string(S), 136)) — equivalent to the reference's precomputed
initial states, which we re-derive rather than copy.
"""

from __future__ import annotations

import struct

M64 = (1 << 64) - 1

_ROTC = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]
_RC = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
    0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
    0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
    0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
    0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]


def _rotl(x, n):
    return ((x << n) | (x >> (64 - n))) & M64


def keccak_f1600(state: list[int]) -> list[int]:
    """FIPS-202 permutation on 25 lanes (5x5, lane (x,y) at index x + 5y)."""
    a = list(state)
    for rc in _RC:
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        for i in range(25):
            a[i] ^= d[i % 5]
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(a[x + 5 * y], _ROTC[x][y])
        for y in range(5):
            row = b[5 * y : 5 * y + 5]
            for x in range(5):
                a[x + 5 * y] = row[x] ^ ((~row[(x + 1) % 5] & M64) & row[(x + 2) % 5])
        a[0] ^= rc
    return a


def _left_encode(n: int) -> bytes:
    b = n.to_bytes((n.bit_length() + 7) // 8 or 1, "big")
    return bytes([len(b)]) + b


def _encode_string(s: bytes) -> bytes:
    return _left_encode(len(s) * 8) + s


def _bytepad(data: bytes, w: int) -> bytes:
    out = _left_encode(w) + data
    if len(out) % w:
        out += b"\x00" * (w - len(out) % w)
    return out


RATE = 136  # cSHAKE256 / SHA3-256-family rate for 512-bit capacity


def cshake256_initial_state(customization: bytes) -> list[int]:
    """State after absorbing the cSHAKE prefix block (N="", S=custom)."""
    prefix = _bytepad(_encode_string(b"") + _encode_string(customization), RATE)
    assert len(prefix) == RATE
    state = [0] * 25
    words = struct.unpack("<17Q", prefix)
    for i, w in enumerate(words):
        state[i] ^= w
    return keccak_f1600(state)


_POW_STATE = None
_HEAVY_STATE = None


def _pow_state():
    global _POW_STATE
    if _POW_STATE is None:
        _POW_STATE = cshake256_initial_state(b"ProofOfWorkHash")
    return _POW_STATE


def _heavy_state():
    global _HEAVY_STATE
    if _HEAVY_STATE is None:
        _HEAVY_STATE = cshake256_initial_state(b"HeavyHash")
    return _HEAVY_STATE


def _absorb_fixed_80(initial: list[int], data80: bytes) -> bytes:
    """Absorb an 80-byte message + cSHAKE padding into a copy of `initial`,
    then squeeze 32 bytes.  80 bytes < RATE so one permutation suffices
    (mirrors PowHash::finalize_with_nonce, pow_hashers.rs:23-38)."""
    state = list(initial)
    words = struct.unpack("<10Q", data80)
    for i, w in enumerate(words):
        state[i] ^= w
    state[10] ^= 0x04  # cSHAKE domain padding byte at position 80
    state[16] ^= 1 << 63  # final bit of the rate block
    state = keccak_f1600(state)
    return struct.pack("<4Q", *state[:4])


def pow_hash(pre_pow_hash: bytes, timestamp: int, nonce: int) -> bytes:
    data = pre_pow_hash + timestamp.to_bytes(8, "little") + b"\x00" * 32 + nonce.to_bytes(8, "little")
    return _absorb_fixed_80(_pow_state(), data)


def heavy_hash(in_hash: bytes) -> bytes:
    """cSHAKE256("HeavyHash") of 32 bytes (single block)."""
    state = list(_heavy_state())
    words = struct.unpack("<4Q", in_hash)
    for i, w in enumerate(words):
        state[i] ^= w
    state[4] ^= 0x04  # padding byte at position 32
    state[16] ^= 1 << 63
    state = keccak_f1600(state)
    return struct.pack("<4Q", *state[:4])


# --- xoshiro256++ and the HeavyHash matrix (consensus/pow/src/) ---


class Xoshiro256PlusPlus:
    def __init__(self, hash32: bytes):
        self.s = list(struct.unpack("<4Q", hash32))

    def next_u64(self) -> int:
        s = self.s
        res = (_rotl((s[0] + s[3]) & M64, 23) + s[0]) & M64
        t = (s[1] << 17) & M64
        s[2] ^= s[0]
        s[3] ^= s[1]
        s[1] ^= s[2]
        s[0] ^= s[3]
        s[2] ^= t
        s[3] = _rotl(s[3], 45)
        return res


class Matrix:
    """64x64 matrix of 4-bit values, generated until full rank (matrix.rs)."""

    def __init__(self, rows: list[list[int]]):
        self.rows = rows

    @staticmethod
    def generate(pre_pow_hash: bytes) -> "Matrix":
        gen = Xoshiro256PlusPlus(pre_pow_hash)
        while True:
            rows = [[0] * 64 for _ in range(64)]
            for i in range(64):
                for j in range(0, 64, 16):
                    val = gen.next_u64()
                    for shift in range(16):
                        rows[i][j + shift] = (val >> (4 * shift)) & 0x0F
            m = Matrix(rows)
            if m.compute_rank() == 64:
                return m

    def compute_rank(self) -> int:
        eps = 1e-9
        mat = [[float(v) for v in row] for row in self.rows]
        rank = 0
        row_selected = [False] * 64
        for i in range(64):
            j = next((j for j in range(64) if not row_selected[j] and abs(mat[j][i]) > eps), None)
            if j is None:
                continue
            rank += 1
            row_selected[j] = True
            for k in range(i + 1, 64):
                mat[j][k] /= mat[j][i]
            for k in range(64):
                if k != j and abs(mat[k][i]) > eps:
                    for l in range(i + 1, 64):
                        mat[k][l] -= mat[j][l] * mat[k][i]
        return rank

    def heavy_hash(self, hash32: bytes) -> bytes:
        # convert hash to 64 nibbles (big-nibble first per byte)
        v = []
        for byte in hash32:
            v.append(byte >> 4)
            v.append(byte & 0x0F)
        products = []
        for i in range(64):
            s = 0
            row = self.rows[i]
            for j in range(64):
                s += row[j] * v[j]
            products.append((s >> 10) & 0x0F)
        # XOR the product nibbles back into the hash bytes
        out = bytearray(hash32)
        for i in range(32):
            out[i] ^= (products[2 * i] << 4) | products[2 * i + 1]
        return heavy_hash(bytes(out))


def calc_block_pow_hash(header) -> bytes:
    """Full PoW value of a header (pow/src/lib.rs State::calculate_pow)."""
    from kaspa_tpu.consensus import hashing as chash

    pre_pow = chash.header_hash_override_nonce_time(header, 0, 0)
    matrix = Matrix.generate(pre_pow)
    first = pow_hash(pre_pow, header.timestamp, header.nonce)
    return matrix.heavy_hash(first)


def check_pow(header) -> bool:
    """pow/src/lib.rs State::check_pow: PoW value (as LE uint) <= target."""
    from kaspa_tpu.consensus.difficulty import compact_to_target

    target = compact_to_target(header.bits)
    value = int.from_bytes(calc_block_pow_hash(header), "little")
    return value <= target
