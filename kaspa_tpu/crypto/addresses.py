"""Kaspa addresses: cashaddr-style bech32 codec.

Reference: crypto/addresses/src/{lib.rs,bech32.rs} — 5-bit charset encoding
with the BCH polymod checksum (8 five-bit checksum symbols), address
versions PubKey (0, 32-byte x-only), PubKeyECDSA (1, 33-byte), ScriptHash
(8, 32-byte blake2b of the redeem script).
"""

from __future__ import annotations

from dataclasses import dataclass

CHARSET = "qpzry9x8gf2tvdw0s3jn54khce6mua7l"
_REV = {c: i for i, c in enumerate(CHARSET)}

VERSION_PUBKEY = 0
VERSION_PUBKEY_ECDSA = 1
VERSION_SCRIPT_HASH = 8

_PAYLOAD_LEN = {VERSION_PUBKEY: 32, VERSION_PUBKEY_ECDSA: 33, VERSION_SCRIPT_HASH: 32}

PREFIX_MAINNET = "kaspa"
PREFIX_TESTNET = "kaspatest"
PREFIX_SIMNET = "kaspasim"
PREFIX_DEVNET = "kaspadev"


class AddressError(Exception):
    pass


def _polymod(values) -> int:
    c = 1
    for d in values:
        c0 = c >> 35
        c = ((c & 0x07FFFFFFFF) << 5) ^ d
        if c0 & 0x01:
            c ^= 0x98F2BC8E61
        if c0 & 0x02:
            c ^= 0x79B76D99E2
        if c0 & 0x04:
            c ^= 0xF33E5FB3C4
        if c0 & 0x08:
            c ^= 0xAE2EABE2A8
        if c0 & 0x10:
            c ^= 0x1E4F43E470
    return c ^ 1


def _checksum(payload5: list[int], prefix: str) -> int:
    stream = [ord(ch) & 0x1F for ch in prefix] + [0] + payload5 + [0] * 8
    return _polymod(stream)


def _conv8to5(data: bytes) -> list[int]:
    out = []
    buff = 0
    bits = 0
    for c in data:
        buff = (buff << 8) | c
        bits += 8
        while bits >= 5:
            bits -= 5
            out.append((buff >> bits) & 0x1F)
            buff &= (1 << bits) - 1
    if bits:
        out.append((buff << (5 - bits)) & 0x1F)
    return out


def _conv5to8(data: list[int]) -> bytes:
    out = bytearray()
    buff = 0
    bits = 0
    for c in data:
        buff = (buff << 5) | c
        bits += 5
        while bits >= 8:
            bits -= 8
            out.append((buff >> bits) & 0xFF)
            buff &= (1 << bits) - 1
    return bytes(out)  # right-side padding ignored


@dataclass(frozen=True)
class Address:
    prefix: str
    version: int
    payload: bytes

    def __post_init__(self):
        expected = _PAYLOAD_LEN.get(self.version)
        if expected is None:
            raise AddressError(f"unknown address version {self.version}")
        if len(self.payload) != expected:
            raise AddressError(f"version {self.version} payload must be {expected} bytes")

    def to_string(self) -> str:
        payload5 = _conv8to5(bytes([self.version]) + self.payload)
        chk = _checksum(payload5, self.prefix)
        chk5 = _conv8to5(chk.to_bytes(8, "big")[3:])
        return self.prefix + ":" + "".join(CHARSET[c] for c in payload5 + chk5)

    @staticmethod
    def from_string(s: str) -> "Address":
        if ":" not in s:
            raise AddressError("missing prefix")
        prefix, body = s.split(":", 1)
        try:
            u5 = [_REV[ch] for ch in body]
        except KeyError as e:
            raise AddressError(f"invalid character {e.args[0]!r}") from None
        if len(u5) < 8:
            raise AddressError("address too short")
        if _checksum(u5[:-8], prefix) != int.from_bytes(_conv5to8(u5[-8:]).rjust(8, b"\x00"), "big"):
            raise AddressError("bad checksum")
        decoded = _conv5to8(u5[:-8])
        if not decoded:
            raise AddressError("empty payload")
        return Address(prefix, decoded[0], decoded[1:])


def pay_to_address_script(address: Address):
    """standard.rs pay_to_address_script."""
    from kaspa_tpu.txscript import standard

    if address.version == VERSION_PUBKEY:
        return standard.pay_to_pub_key(address.payload)
    if address.version == VERSION_PUBKEY_ECDSA:
        return standard.pay_to_pub_key_ecdsa(address.payload)
    if address.version == VERSION_SCRIPT_HASH:
        from kaspa_tpu.consensus.model import ScriptPublicKey

        return ScriptPublicKey(
            0,
            bytes([standard.OP_BLAKE2B, standard.OP_DATA_32]) + address.payload + bytes([standard.OP_EQUAL]),
        )
    raise AddressError(f"unknown version {address.version}")


def extract_script_pub_key_address(spk, prefix: str) -> Address:
    """standard.rs extract_script_pub_key_address."""
    from kaspa_tpu.txscript import standard

    cls = standard.classify_script(spk)
    if cls == standard.ScriptClass.PUB_KEY:
        return Address(prefix, VERSION_PUBKEY, spk.script[1:33])
    if cls == standard.ScriptClass.PUB_KEY_ECDSA:
        return Address(prefix, VERSION_PUBKEY_ECDSA, spk.script[1:34])
    if cls == standard.ScriptClass.SCRIPT_HASH:
        return Address(prefix, VERSION_SCRIPT_HASH, spk.script[2:34])
    raise AddressError("nonstandard script")
