"""Pure-Python secp256k1 + BIP340 Schnorr + ECDSA (host reference / oracle).

Textbook implementation over python ints.  Used as the golden oracle for the
TPU kernels, and by host-side tooling (wallet signing, test fixtures).
Mirrors the behaviour of the reference's libsecp256k1 usage in
crypto/txscript/src/lib.rs:885-935:

- Schnorr: BIP340 x-only keys, challenge = tagged SHA256("BIP0340/challenge").
- ECDSA: 33-byte compressed pubkeys, 64-byte compact signatures; high-S
  signatures are rejected (libsecp256k1's secp256k1_ecdsa_verify semantics).
"""

from __future__ import annotations

import hashlib

P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
G = (GX, GY)

Point = "tuple[int, int] | None"  # affine; None == identity


def point_add(a, b):
    if a is None:
        return b
    if b is None:
        return a
    x1, y1 = a
    x2, y2 = b
    if x1 == x2 and (y1 + y2) % P == 0:
        return None
    if a == b:
        lam = (3 * x1 * x1) * pow(2 * y1, -1, P) % P
    else:
        lam = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def point_mul(p, k):
    r = None
    while k:
        if k & 1:
            r = point_add(r, p)
        p = point_add(p, p)
        k >>= 1
    return r


def is_on_curve(p) -> bool:
    if p is None:
        return True
    x, y = p
    return (y * y - x * x * x - 7) % P == 0


def lift_x(x: int):
    """BIP340 lift_x: even-y point with the given x, or None."""
    if x >= P:
        return None
    y_sq = (pow(x, 3, P) + 7) % P
    y = pow(y_sq, (P + 1) // 4, P)
    if y * y % P != y_sq:
        return None
    return (x, y if y % 2 == 0 else P - y)


def tagged_hash(tag: str, data: bytes) -> bytes:
    th = hashlib.sha256(tag.encode()).digest()
    return hashlib.sha256(th + th + data).digest()


def schnorr_pubkey(seckey: int) -> bytes:
    p = point_mul(G, seckey)
    return p[0].to_bytes(32, "big")


def schnorr_sign(msg32: bytes, seckey: int, aux32: bytes = b"\x00" * 32) -> bytes:
    """BIP340 signing (for tests/wallet; verification is the consensus path)."""
    d0 = seckey
    pt = point_mul(G, d0)
    d = d0 if pt[1] % 2 == 0 else N - d0
    t = d ^ int.from_bytes(tagged_hash("BIP0340/aux", aux32), "big")
    k0 = (
        int.from_bytes(
            tagged_hash("BIP0340/nonce", t.to_bytes(32, "big") + pt[0].to_bytes(32, "big") + msg32), "big"
        )
        % N
    )
    if k0 == 0:
        raise ValueError("zero nonce")
    r_pt = point_mul(G, k0)
    k = k0 if r_pt[1] % 2 == 0 else N - k0
    e = (
        int.from_bytes(
            tagged_hash("BIP0340/challenge", r_pt[0].to_bytes(32, "big") + pt[0].to_bytes(32, "big") + msg32),
            "big",
        )
        % N
    )
    sig = r_pt[0].to_bytes(32, "big") + ((k + e * d) % N).to_bytes(32, "big")
    assert schnorr_verify(pt[0].to_bytes(32, "big"), msg32, sig)
    return sig


def schnorr_verify(pubkey32: bytes, msg32: bytes, sig64: bytes) -> bool:
    if len(pubkey32) != 32 or len(sig64) != 64:
        return False
    pk = lift_x(int.from_bytes(pubkey32, "big"))
    if pk is None:
        return False
    r = int.from_bytes(sig64[:32], "big")
    s = int.from_bytes(sig64[32:], "big")
    if r >= P or s >= N:
        return False
    e = int.from_bytes(tagged_hash("BIP0340/challenge", sig64[:32] + pubkey32 + msg32), "big") % N
    rp = point_add(point_mul(G, s), point_mul((pk[0], P - pk[1]), e))
    return rp is not None and rp[1] % 2 == 0 and rp[0] == r


def ecdsa_pubkey(seckey: int) -> bytes:
    x, y = point_mul(G, seckey)
    return bytes([2 + (y & 1)]) + x.to_bytes(32, "big")


def parse_compressed(pubkey33: bytes):
    if len(pubkey33) != 33 or pubkey33[0] not in (2, 3):
        return None
    x = int.from_bytes(pubkey33[1:], "big")
    p = lift_x(x)
    if p is None:
        return None
    x, y = p
    if (y & 1) != (pubkey33[0] & 1):
        y = P - y
    return (x, y)


def ecdsa_sign(msg32: bytes, seckey: int, nonce: int) -> bytes:
    z = int.from_bytes(msg32, "big") % N
    r_pt = point_mul(G, nonce)
    r = r_pt[0] % N
    s = pow(nonce, -1, N) * (z + r * seckey) % N
    if s > N // 2:
        s = N - s  # low-S normalization (libsecp256k1 signing behaviour)
    if r == 0 or s == 0:
        raise ValueError("bad nonce")
    return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def ecdsa_verify(pubkey33: bytes, msg32: bytes, sig64: bytes) -> bool:
    pk = parse_compressed(pubkey33)
    if pk is None or len(sig64) != 64:
        return False
    r = int.from_bytes(sig64[:32], "big")
    s = int.from_bytes(sig64[32:], "big")
    if not (1 <= r < N and 1 <= s < N):
        return False
    if s > N // 2:
        return False  # libsecp256k1 rejects non-normalized (high-S) signatures
    z = int.from_bytes(msg32, "big") % N
    si = pow(s, -1, N)
    rp = point_add(point_mul(G, z * si % N), point_mul(pk, r * si % N))
    return rp is not None and rp[0] % N == r
