"""Host-side batched signature verification front-end.

This is the framework's "communication backend" between the validation
pipeline and the TPU: it marshals (pubkey, msg, sig) triples into fixed
shape device arrays, dispatches the jitted kernels, and hands back a
validity bitmask the validator consumes unchanged — mirroring the role of
libsecp256k1 calls inside the reference's script engine
(crypto/txscript/src/lib.rs:885-935) but batched across a whole block/DAG
slice instead of per-input.
"""

from __future__ import annotations

import hashlib
import os
import threading
from dataclasses import dataclass, field

import numpy as np

from kaspa_tpu.crypto import eclib
from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import PERCENT_BUCKETS, REGISTRY, SIZE_BUCKETS
from kaspa_tpu.ops import bigint as bi
from kaspa_tpu.ops.secp256k1 import points as pt
from kaspa_tpu.ops.secp256k1.verify import ecdsa_verify, schnorr_verify
from kaspa_tpu.resilience import supervisor
from kaspa_tpu.resilience.breaker import HUNG, device_breaker
from kaspa_tpu.resilience.faults import FAULTS

# batch shape telemetry: occupancy is the fraction of padded device lanes
# doing useful work, the quantity batch-verify throughput is dominated by
# (committee-consensus signature studies measure exactly this); dispatched
# shapes proxy XLA recompiles — every new bucket is a fresh jit trace
_BATCH_SIZE = REGISTRY.histogram("secp_batch_size", SIZE_BUCKETS, help="logical verify jobs per device batch")
_OCCUPANCY = REGISTRY.histogram(
    "secp_batch_occupancy_pct", PERCENT_BUCKETS, help="logical batch size / padded bucket size * 100"
)
_PADDED_LANES = REGISTRY.counter("secp_padded_lanes", help="device lanes wasted on pad-to-bucket")
_NEW_SHAPES = REGISTRY.counter_family(
    "secp_dispatch_shapes", "kernel", help="distinct padded bucket sizes dispatched (jit recompile proxy)"
)
_COLD_SPLITS = REGISTRY.counter_family(
    "secp_cold_bucket_splits", "kernel",
    help="batches split into warm-bucket sub-dispatches to dodge a cold jit compile",
)
_seen_shapes: set = set()

# thread-local escape hatch: pretrace_bucket() deliberately compiles a
# cold bucket, so it must bypass the warm-bucket split
_force_tls = threading.local()


def _cold_split_enabled() -> bool:
    """Warm-bucket splitting: a batch whose padded bucket was never
    compiled is split into sub-dispatches at the largest already-warm
    bucket instead of paying the compile wall inline.  The verify-kernel
    jit cost grows superlinearly with batch width on the XLA formulation
    (the wedge dossiers' recurring probe stall; ~3 min for bucket 16 on
    CPU), so crossing into a cold bucket mid-pipeline can stall the
    commit lock for minutes.  `KASPA_TPU_COLD_BUCKET_SPLIT=0` restores
    pad-up-and-compile — bench sweeps that deliberately measure specific
    bucket shapes need that."""
    return os.environ.get("KASPA_TPU_COLD_BUCKET_SPLIT", "1") not in ("0", "off", "false")

# degraded-lane occupancy: how much of the verify workload is riding the
# host oracle instead of the device (breaker open, or a dispatch died) —
# the quantity the hostile-load sustain run reports
_DEGRADED_DISPATCHES = REGISTRY.counter(
    "secp_degraded_dispatches", help="batches routed to the host degraded lane (breaker open / dispatch failed)"
)
_DEGRADED_JOBS = REGISTRY.counter("secp_degraded_jobs", help="verify jobs executed on the host degraded lane")

W = bi.FP.W
_CHALLENGE_MID = hashlib.sha256(
    hashlib.sha256(b"BIP0340/challenge").digest() * 2
)  # pre-tagged sha256 state


def _bucket(n: int) -> int:
    """Pad batch sizes to powers of two (min 8) to bound jit recompiles."""
    b = 8
    while b < n:
        b <<= 1
    return b


def schnorr_challenge(r32: bytes, px32: bytes, msg32: bytes) -> int:
    h = _CHALLENGE_MID.copy()
    h.update(r32 + px32 + msg32)
    return int.from_bytes(h.digest(), "big") % eclib.N


_ZERO32 = b"\x00" * 32


def _be32_to_limbs(col, b):
    """[N x 32-byte big-endian] -> [bucket, 16] int32 LE 16-bit limbs (vectorised)."""
    out = np.zeros((b, W), np.int32)
    if col:
        arr = np.frombuffer(b"".join(col), dtype=np.uint8).reshape(len(col), 32)
        out[: len(col)] = arr[:, ::-1].copy().view("<u2").astype(np.int32)
    return out


@dataclass
class _Batch:
    """Marshals verification jobs into the device batch layout.

    The host-side "pinned buffer" packing is numpy-vectorised: 32-byte
    big-endian field elements -> int32 limb / window-digit arrays without
    per-item python loops (the host half of the FFI batch boundary).
    """

    px: list = field(default_factory=list)  # 32B BE x-coordinates
    py: list = field(default_factory=list)
    rc: list = field(default_factory=list)  # canonical target (r or r mod n)
    d1: list = field(default_factory=list)  # s / u1 scalars (python ints mod n)
    d2: list = field(default_factory=list)  # e / u2 scalars (python ints mod n)
    ok: list = field(default_factory=list)

    def push_invalid(self):
        self.px.append(_ZERO32)
        self.py.append(_ZERO32)
        self.rc.append(_ZERO32)
        self.d1.append(0)
        self.d2.append(0)
        self.ok.append(False)

    def push(self, px: int, py: int, rc: int, s1: int, s2: int):
        self.px.append(px.to_bytes(32, "big"))
        self.py.append(py.to_bytes(32, "big"))
        self.rc.append(rc.to_bytes(32, "big"))
        self.d1.append(s1)
        self.d2.append(s2)
        self.ok.append(True)

    def run(self, kernel):
        n = len(self.ok)
        if n == 0:
            return np.zeros(0, dtype=bool)
        b = _bucket(n)
        shape_key = (kernel.__name__, b)
        new_shape = shape_key not in _seen_shapes
        if new_shape and _cold_split_enabled() and not getattr(_force_tls, "on", False):
            warm = max(
                (bk for k, bk in _seen_shapes if k == kernel.__name__ and bk < b),
                default=None,
            )
            if warm is not None:
                _COLD_SPLITS.inc(kernel.__name__)
                return self._run_split(kernel, warm)
        _BATCH_SIZE.observe(n)
        _OCCUPANCY.observe(100.0 * n / b)
        _PADDED_LANES.inc(b - n)
        if new_shape:
            _seen_shapes.add(shape_key)
            _NEW_SHAPES.inc(kernel.__name__)
        ok = np.zeros(b, dtype=bool)
        ok[:n] = self.ok
        pad = [0] * (b - n)
        args = (
            _be32_to_limbs(self.px, b),
            _be32_to_limbs(self.py, b),
            _be32_to_limbs(self.rc, b),
            self.d1 + pad,
            self.d2 + pad,
            ok,
        )
        if new_shape:
            # first dispatch of a (kernel, bucket) shape pays the XLA
            # trace+compile; surfacing it as a span is what lets a wedge
            # dossier / flight trace say *where* a probe stalled
            try:
                with trace.span("secp.jit_compile", kernel=kernel.__name__, bucket=b):
                    FAULTS.fire("device.jit_compile")
                    mask = kernel(*args)
            except BaseException:
                # a compile that failed (or was abandoned by the watchdog)
                # must not leave the shape marked warm — the next dispatch
                # would skip the split and pay a surprise compile wall
                _seen_shapes.discard(shape_key)
                raise
            supervisor.note_shape(kernel.__name__, b)
        else:
            mask = kernel(*args)
        return np.asarray(mask)[:n]

    def _run_split(self, kernel, warm: int) -> np.ndarray:
        """Dispatch this batch as sub-batches of the given warm bucket
        size — several known-compiled round trips instead of one cold
        compile.  Sub-batches recurse through run(): a full slice reuses
        the warm shape, the tail pads into a smaller (also warm) bucket."""
        n = len(self.ok)
        out = np.empty(n, dtype=bool)
        for off in range(0, n, warm):
            end = min(off + warm, n)
            sub = _Batch(
                px=self.px[off:end],
                py=self.py[off:end],
                rc=self.rc[off:end],
                d1=self.d1[off:end],
                d2=self.d2[off:end],
                ok=self.ok[off:end],
            )
            out[off:end] = sub.run(kernel)
        return out


def _dispatch_tier(kernel, n: int) -> str:
    """Watchdog tier: a never-seen (kernel, bucket) shape legitimately
    pays an XLA compile, so it gets the long deadline."""
    return "dispatch" if (kernel.__name__, _bucket(n)) in _seen_shapes else "compile"


def _run_guarded(batch: _Batch, kernel, items: list, host_verify) -> np.ndarray:
    """Dispatch through the watchdog and the device circuit breaker.

    CLOSED/probing: the device runs the batch on a supervised worker
    thread; a dispatch exception (wedged chip, XLA error, injected fault)
    counts toward a trip, while a watchdog deadline trips immediately
    with cause ``hung`` and the batch — never lost, never double-resolved
    — requeues below.  OPEN: the host degraded lane verifies each raw
    triple with the eclib oracle — same acceptance decisions, host
    throughput — until a canary probe succeeds and the breaker re-arms.
    """
    n = len(batch.ok)
    if n == 0:
        return np.zeros(0, dtype=bool)
    br = device_breaker()
    if br.allow():
        try:
            mask = supervisor.run_supervised(
                lambda: batch.run(kernel),
                tier=_dispatch_tier(kernel, n),
                kernel=kernel.__name__,
                jobs=n,
            )
        except supervisor.DeviceHangError:
            br.record_failure(cause=HUNG)
            supervisor.note_requeue(n)
        except Exception:  # noqa: BLE001 - device boundary: any failure trips
            br.record_failure()
        else:
            br.record_success()
            return mask
    return _host_lane(batch, kernel.__name__, items, host_verify)


def _host_lane(batch: _Batch, kernel_name: str, items: list, host_verify) -> np.ndarray:
    """The bit-identical host degraded lane: same prechecks as the device
    path (already folded into ``batch.ok``), per-item eclib oracle verify
    for the survivors.  Shared by the breaker-open path above and the
    fabric balancer's last failover tier."""
    n = len(batch.ok)
    _DEGRADED_DISPATCHES.inc()
    _DEGRADED_JOBS.inc(n)
    with trace.span("secp.degraded_dispatch", kernel=kernel_name, jobs=n):
        mask = np.zeros(n, dtype=bool)
        for i, (pub, msg, sig) in enumerate(items):
            if batch.ok[i]:  # host-precheck failures stay False
                mask[i] = bool(host_verify(pub, msg, sig))
    return mask


def _build_schnorr_batch(items: list) -> _Batch:
    batch = _Batch()
    for pub, msg, sig in items:
        # BIP340 allows arbitrary-length messages (matching eclib oracle);
        # kaspa consensus always passes 32-byte sighash digests.
        if len(pub) != 32 or len(sig) != 64:
            batch.push_invalid()
            continue
        pk = eclib.lift_x(int.from_bytes(pub, "big"))
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if pk is None or r >= eclib.P or s >= eclib.N:
            batch.push_invalid()
            continue
        e = schnorr_challenge(sig[:32], pub, msg)
        batch.push(pk[0], pk[1], r, s, e)
    return batch


def schnorr_verify_batch(items) -> np.ndarray:
    """items: iterable of (pubkey32, msg32, sig64) -> bool mask.

    Encoding/range checks and lift_x run on host (failures short-circuit to
    False without occupying useful device lanes beyond padding).
    """
    items = list(items)
    return _run_guarded(_build_schnorr_batch(items), schnorr_verify, items, eclib.schnorr_verify)


def _build_ecdsa_batch(items: list) -> _Batch:
    batch = _Batch()
    half_n = eclib.N // 2
    for pub, msg, sig in items:
        if len(sig) != 64 or len(msg) != 32:
            batch.push_invalid()
            continue
        pk = eclib.parse_compressed(pub)
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if pk is None or not (1 <= r < eclib.N) or not (1 <= s < eclib.N) or s > half_n:
            batch.push_invalid()
            continue
        z = int.from_bytes(msg, "big") % eclib.N
        si = pow(s, -1, eclib.N)
        u1 = z * si % eclib.N
        u2 = r * si % eclib.N
        batch.push(pk[0], pk[1], r, u1, u2)
    return batch


def ecdsa_verify_batch(items) -> np.ndarray:
    """items: iterable of (pubkey33, msg32, sig64_compact) -> bool mask."""
    items = list(items)
    return _run_guarded(_build_ecdsa_batch(items), ecdsa_verify, items, eclib.ecdsa_verify)


def verify_batch(kind: str, items) -> np.ndarray:
    """Kind-dispatching batched verify ("schnorr" | "ecdsa") — the entry
    the verify fabric's slice workers call with wire-decoded triples."""
    return (schnorr_verify_batch if kind == "schnorr" else ecdsa_verify_batch)(items)


def host_verify_batch(kind: str, items) -> np.ndarray:
    """Host-only verify for one super-batch: the same precheck + eclib
    oracle lane the breaker-open path runs, callable directly.  This is
    the fabric balancer's final failover tier — every slice dead or hung
    still yields bit-identical acceptance decisions, just at host
    throughput, and it can never touch a (possibly wedged) device."""
    items = list(items)
    if kind == "schnorr":
        return _host_lane(_build_schnorr_batch(items), "schnorr_verify", items, eclib.schnorr_verify)
    return _host_lane(_build_ecdsa_batch(items), "ecdsa_verify", items, eclib.ecdsa_verify)


# --- supervision hooks ----------------------------------------------------

_CANARY_SECKEY = int.from_bytes(hashlib.sha256(b"kaspa-tpu canary").digest(), "big") % eclib.N or 1


def _canary_items(count: int = 2) -> list:
    """Tiny known-answer workload (fixed key, distinct messages): every
    signature is valid, so a canary dispatch must return an all-True mask."""
    pub = eclib.schnorr_pubkey(_CANARY_SECKEY)
    out = []
    for i in range(count):
        msg = hashlib.sha256(b"canary-msg-%d" % i).digest()
        out.append((pub, msg, eclib.schnorr_sign(msg, _CANARY_SECKEY)))
    return out


def canary_probe() -> bool:
    """One supervised device dispatch of the known-answer batch — the
    prober's HALF_OPEN probe.  Bypasses the breaker gate (the prober holds
    the probe slot) and runs with fault injection suppressed so drills
    keep their requeued==injected accounting.  True iff the device
    answered correctly within the watchdog deadline."""
    from kaspa_tpu.resilience import faults as faults_mod

    items = _canary_items()
    batch = _build_schnorr_batch(items)

    def _dispatch():
        with faults_mod.suppress():
            return batch.run(schnorr_verify)

    mask = supervisor.run_supervised(
        _dispatch,
        tier=_dispatch_tier(schnorr_verify, len(items)),
        kernel="schnorr_verify",
        jobs=len(items),
    )
    return bool(np.asarray(mask).all())


_PRETRACE_KERNELS = {"schnorr_verify": schnorr_verify, "ecdsa_verify": ecdsa_verify}


def pretrace_bucket(kernel_name: str, bucket: int) -> str:
    """Compile one (kernel, bucket) shape ahead of traffic (warm-manifest
    restart path).  Dispatches an all-invalid batch of exactly ``bucket``
    jobs with the warm-split bypassed so the target shape itself compiles;
    runs under the watchdog's compile tier.  Returns "warm" (already
    compiled this process), "traced", or "error:...".
    """
    kernel = _PRETRACE_KERNELS.get(kernel_name)
    if kernel is None or bucket < 8:
        return f"error:unknown {kernel_name}/{bucket}"
    if (kernel_name, bucket) in _seen_shapes:
        return "warm"
    batch = _Batch()
    for _ in range(bucket):
        batch.push_invalid()

    def _dispatch():
        from kaspa_tpu.resilience import faults as faults_mod

        _force_tls.on = True
        try:
            with faults_mod.suppress():
                return batch.run(kernel)
        finally:
            _force_tls.on = False

    try:
        supervisor.run_supervised(_dispatch, tier="compile", kernel=kernel_name, jobs=bucket)
    except Exception as e:  # noqa: BLE001 - pretrace is best-effort
        return f"error:{type(e).__name__}"
    return "traced"
