"""Host-side batched signature verification front-end.

This is the framework's "communication backend" between the validation
pipeline and the TPU: it marshals (pubkey, msg, sig) triples into fixed
shape device arrays, dispatches the jitted kernels, and hands back a
validity bitmask the validator consumes unchanged — mirroring the role of
libsecp256k1 calls inside the reference's script engine
(crypto/txscript/src/lib.rs:885-935) but batched across a whole block/DAG
slice instead of per-input.
"""

from __future__ import annotations

import hashlib
import os
import threading
from dataclasses import dataclass, field

import numpy as np

from kaspa_tpu.crypto import eclib
from kaspa_tpu.observability import trace
from kaspa_tpu.observability.core import PERCENT_BUCKETS, REGISTRY, SIZE_BUCKETS
from kaspa_tpu.ops import bigint as bi
from kaspa_tpu.ops.secp256k1 import points as pt
from kaspa_tpu.ops.secp256k1.verify import _scalars_to_digits, ecdsa_verify, schnorr_verify
from kaspa_tpu.resilience import supervisor
from kaspa_tpu.resilience.breaker import HUNG, device_breaker
from kaspa_tpu.resilience.faults import FAULTS

# batch shape telemetry: occupancy is the fraction of padded device lanes
# doing useful work, the quantity batch-verify throughput is dominated by
# (committee-consensus signature studies measure exactly this); dispatched
# shapes proxy XLA recompiles — every new bucket is a fresh jit trace
_BATCH_SIZE = REGISTRY.histogram("secp_batch_size", SIZE_BUCKETS, help="logical verify jobs per device batch")
_OCCUPANCY = REGISTRY.histogram(
    "secp_batch_occupancy_pct", PERCENT_BUCKETS, help="logical batch size / padded bucket size * 100"
)
_PADDED_LANES = REGISTRY.counter("secp_padded_lanes", help="device lanes wasted on pad-to-bucket")
_NEW_SHAPES = REGISTRY.counter_family(
    "secp_dispatch_shapes", "kernel", help="distinct padded bucket sizes dispatched (jit recompile proxy)"
)
_COLD_SPLITS = REGISTRY.counter_family(
    "secp_cold_bucket_splits", "kernel",
    help="batches split into warm-bucket sub-dispatches to dodge a cold jit compile",
)
_seen_shapes: set = set()

# thread-local escape hatch: pretrace_bucket() deliberately compiles a
# cold bucket, so it must bypass the warm-bucket split
_force_tls = threading.local()


def _cold_split_enabled() -> bool:
    """Warm-bucket splitting: a batch whose padded bucket was never
    compiled is split into sub-dispatches at the largest already-warm
    bucket instead of paying the compile wall inline.  The verify-kernel
    jit cost grows superlinearly with batch width on the XLA formulation
    (the wedge dossiers' recurring probe stall; ~3 min for bucket 16 on
    CPU), so crossing into a cold bucket mid-pipeline can stall the
    commit lock for minutes.  `KASPA_TPU_COLD_BUCKET_SPLIT=0` restores
    pad-up-and-compile — bench sweeps that deliberately measure specific
    bucket shapes need that."""
    return os.environ.get("KASPA_TPU_COLD_BUCKET_SPLIT", "1") not in ("0", "off", "false")

# aggregate-lane telemetry: one RLC multi-scalar check replaces a whole
# batch of dual ladders, so throughput lives or dies on how often the
# combined check passes outright vs decays into bisection
_AGG_BATCHES = REGISTRY.counter("secp_aggregate_batches", help="batches verified through the aggregate RLC lane")
_AGG_JOBS = REGISTRY.counter("secp_aggregate_jobs", help="verify jobs entering the aggregate RLC lane")
_AGG_CHECKS = REGISTRY.counter(
    "secp_aggregate_checks", help="device dispatches of the combined multi-scalar check (incl. bisect halves)"
)
_AGG_BISECT_STEPS = REGISTRY.counter(
    "secp_aggregate_bisect_steps", help="failed aggregate checks split in half to isolate bad signatures"
)
_AGG_LEAF_JOBS = REGISTRY.counter(
    "secp_aggregate_leaf_jobs", help="jobs resolved by per-signature ladder leaves of the bisection"
)
_AGG_FALLBACK_JOBS = REGISTRY.counter(
    "secp_aggregate_fallback_jobs", help="aggregate-lane jobs that fell back to the host degraded lane"
)

# degraded-lane occupancy: how much of the verify workload is riding the
# host oracle instead of the device (breaker open, or a dispatch died) —
# the quantity the hostile-load sustain run reports
_DEGRADED_DISPATCHES = REGISTRY.counter(
    "secp_degraded_dispatches", help="batches routed to the host degraded lane (breaker open / dispatch failed)"
)
_DEGRADED_JOBS = REGISTRY.counter("secp_degraded_jobs", help="verify jobs executed on the host degraded lane")

W = bi.FP.W
_CHALLENGE_MID = hashlib.sha256(
    hashlib.sha256(b"BIP0340/challenge").digest() * 2
)  # pre-tagged sha256 state


def _bucket(n: int) -> int:
    """Pad batch sizes to powers of two (min 8) to bound jit recompiles."""
    b = 8
    while b < n:
        b <<= 1
    return b


def schnorr_challenge(r32: bytes, px32: bytes, msg32: bytes) -> int:
    h = _CHALLENGE_MID.copy()
    h.update(r32 + px32 + msg32)
    return int.from_bytes(h.digest(), "big") % eclib.N


_ZERO32 = b"\x00" * 32


def _be32_to_limbs(col, b):
    """[N x 32-byte big-endian] -> [bucket, 16] int32 LE 16-bit limbs (vectorised)."""
    out = np.zeros((b, W), np.int32)
    if col:
        arr = np.frombuffer(b"".join(col), dtype=np.uint8).reshape(len(col), 32)
        out[: len(col)] = arr[:, ::-1].copy().view("<u2").astype(np.int32)
    return out


@dataclass
class _Batch:
    """Marshals verification jobs into the device batch layout.

    The host-side "pinned buffer" packing is numpy-vectorised: 32-byte
    big-endian field elements -> int32 limb / window-digit arrays without
    per-item python loops (the host half of the FFI batch boundary).
    """

    px: list = field(default_factory=list)  # 32B BE x-coordinates
    py: list = field(default_factory=list)
    rc: list = field(default_factory=list)  # canonical target (r or r mod n)
    d1: list = field(default_factory=list)  # s / u1 scalars (python ints mod n)
    d2: list = field(default_factory=list)  # e / u2 scalars (python ints mod n)
    ok: list = field(default_factory=list)

    def push_invalid(self):
        self.px.append(_ZERO32)
        self.py.append(_ZERO32)
        self.rc.append(_ZERO32)
        self.d1.append(0)
        self.d2.append(0)
        self.ok.append(False)

    def push(self, px: int, py: int, rc: int, s1: int, s2: int):
        self.px.append(px.to_bytes(32, "big"))
        self.py.append(py.to_bytes(32, "big"))
        self.rc.append(rc.to_bytes(32, "big"))
        self.d1.append(s1)
        self.d2.append(s2)
        self.ok.append(True)

    def run(self, kernel):
        n = len(self.ok)
        if n == 0:
            return np.zeros(0, dtype=bool)
        b = _bucket(n)
        shape_key = (kernel.__name__, b)
        new_shape = shape_key not in _seen_shapes
        if new_shape and _cold_split_enabled() and not getattr(_force_tls, "on", False):
            warm = max(
                (bk for k, bk in _seen_shapes if k == kernel.__name__ and bk < b),
                default=None,
            )
            if warm is not None:
                _COLD_SPLITS.inc(kernel.__name__)
                return self._run_split(kernel, warm)
        _BATCH_SIZE.observe(n)
        _OCCUPANCY.observe(100.0 * n / b)
        _PADDED_LANES.inc(b - n)
        if new_shape:
            _seen_shapes.add(shape_key)
            _NEW_SHAPES.inc(kernel.__name__)
        ok = np.zeros(b, dtype=bool)
        ok[:n] = self.ok
        pad = [0] * (b - n)
        args = (
            _be32_to_limbs(self.px, b),
            _be32_to_limbs(self.py, b),
            _be32_to_limbs(self.rc, b),
            self.d1 + pad,
            self.d2 + pad,
            ok,
        )
        if new_shape:
            # first dispatch of a (kernel, bucket) shape pays the XLA
            # trace+compile; surfacing it as a span is what lets a wedge
            # dossier / flight trace say *where* a probe stalled
            try:
                with trace.span("secp.jit_compile", kernel=kernel.__name__, bucket=b):
                    FAULTS.fire("device.jit_compile")
                    mask = kernel(*args)
            except BaseException:
                # a compile that failed (or was abandoned by the watchdog)
                # must not leave the shape marked warm — the next dispatch
                # would skip the split and pay a surprise compile wall
                _seen_shapes.discard(shape_key)
                raise
            supervisor.note_shape(
                kernel.__name__, b,
                family="ecdsa" if "ecdsa" in kernel.__name__ else "ladder",
            )
        else:
            mask = kernel(*args)
        return np.asarray(mask)[:n]

    def _run_split(self, kernel, warm: int) -> np.ndarray:
        """Dispatch this batch as sub-batches of the given warm bucket
        size — several known-compiled round trips instead of one cold
        compile.  Sub-batches recurse through run(): a full slice reuses
        the warm shape, the tail pads into a smaller (also warm) bucket."""
        n = len(self.ok)
        out = np.empty(n, dtype=bool)
        for off in range(0, n, warm):
            end = min(off + warm, n)
            sub = _Batch(
                px=self.px[off:end],
                py=self.py[off:end],
                rc=self.rc[off:end],
                d1=self.d1[off:end],
                d2=self.d2[off:end],
                ok=self.ok[off:end],
            )
            out[off:end] = sub.run(kernel)
        return out


def _dispatch_tier(kernel, n: int) -> str:
    """Watchdog tier: a never-seen (kernel, bucket) shape legitimately
    pays an XLA compile, so it gets the long deadline."""
    return "dispatch" if (kernel.__name__, _bucket(n)) in _seen_shapes else "compile"


def _run_guarded(batch: _Batch, kernel, items: list, host_verify) -> np.ndarray:
    """Dispatch through the watchdog and the device circuit breaker.

    CLOSED/probing: the device runs the batch on a supervised worker
    thread; a dispatch exception (wedged chip, XLA error, injected fault)
    counts toward a trip, while a watchdog deadline trips immediately
    with cause ``hung`` and the batch — never lost, never double-resolved
    — requeues below.  OPEN: the host degraded lane verifies each raw
    triple with the eclib oracle — same acceptance decisions, host
    throughput — until a canary probe succeeds and the breaker re-arms.
    """
    n = len(batch.ok)
    if n == 0:
        return np.zeros(0, dtype=bool)
    br = device_breaker()
    if br.allow():
        try:
            mask = supervisor.run_supervised(
                lambda: batch.run(kernel),
                tier=_dispatch_tier(kernel, n),
                kernel=kernel.__name__,
                jobs=n,
            )
        except supervisor.DeviceHangError:
            br.record_failure(cause=HUNG)
            supervisor.note_requeue(n)
        except Exception:  # noqa: BLE001 - device boundary: any failure trips
            br.record_failure()
        else:
            br.record_success()
            return mask
    return _host_lane(batch, kernel.__name__, items, host_verify)


def _host_lane(batch: _Batch, kernel_name: str, items: list, host_verify) -> np.ndarray:
    """The bit-identical host degraded lane: same prechecks as the device
    path (already folded into ``batch.ok``), per-item eclib oracle verify
    for the survivors.  Shared by the breaker-open path above and the
    fabric balancer's last failover tier."""
    n = len(batch.ok)
    _DEGRADED_DISPATCHES.inc()
    _DEGRADED_JOBS.inc(n)
    with trace.span("secp.degraded_dispatch", kernel=kernel_name, jobs=n):
        mask = np.zeros(n, dtype=bool)
        for i, (pub, msg, sig) in enumerate(items):
            if batch.ok[i]:  # host-precheck failures stay False
                mask[i] = bool(host_verify(pub, msg, sig))
    return mask


def _build_schnorr_batch(items: list) -> _Batch:
    batch = _Batch()
    for pub, msg, sig in items:
        # BIP340 allows arbitrary-length messages (matching eclib oracle);
        # kaspa consensus always passes 32-byte sighash digests.
        if len(pub) != 32 or len(sig) != 64:
            batch.push_invalid()
            continue
        pk = eclib.lift_x(int.from_bytes(pub, "big"))
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if pk is None or r >= eclib.P or s >= eclib.N:
            batch.push_invalid()
            continue
        e = schnorr_challenge(sig[:32], pub, msg)
        # s rides as its canonical 32-byte wire encoding (range-checked
        # above): _scalars_to_digits takes it with zero per-item int work
        batch.push(pk[0], pk[1], r, sig[32:], e)
    return batch


def schnorr_verify_batch(items) -> np.ndarray:
    """items: iterable of (pubkey32, msg32, sig64) -> bool mask.

    Encoding/range checks and lift_x run on host (failures short-circuit to
    False without occupying useful device lanes beyond padding).
    """
    items = list(items)
    return _run_guarded(_build_schnorr_batch(items), schnorr_verify, items, eclib.schnorr_verify)


# --- aggregated random-linear-combination verification ---------------------
#
# ops/secp256k1/aggregate.py holds the math; this is the host half: weight
# derivation, scalar prep, the guarded device dispatch, and the bisection
# that converges a failed combined check back to the exact per-signature
# mask (so verify_batch semantics are unchanged between modes).

_AGG_KERNEL_NAME = "schnorr_aggregate"
_AGG_WEIGHT_BYTES = 16  # 128-bit weights: cancellation probability 2^-128
# below this many live lanes a sub-aggregate stops paying off (two device
# round trips per level vs one ladder dispatch) — resolve per-signature
_AGG_LEAF = 8


@dataclass
class _AggBatch:
    """Host prep for the aggregate lane: negated points + raw scalars.

    pxn/pyn are -P_i (lifted pubkey, y negated), rxn/ryn are -R_i with
    R_i = lift_x(r_i) — negation on host so the device only ever adds.
    """

    pxn: list = field(default_factory=list)  # 32B BE x(-P) == x(P)
    pyn: list = field(default_factory=list)  # 32B BE p - y(P)
    rxn: list = field(default_factory=list)
    ryn: list = field(default_factory=list)
    s: list = field(default_factory=list)  # sig s scalars (python ints)
    e: list = field(default_factory=list)  # challenge scalars
    ok: list = field(default_factory=list)


def _build_schnorr_aggregate(items: list) -> _AggBatch:
    """Same prechecks as _build_schnorr_batch, plus the r -> R_i lift the
    aggregate equation needs as an explicit point."""
    batch = _AggBatch()
    for pub, msg, sig in items:
        if len(pub) != 32 or len(sig) != 64:
            batch.ok.append(False)
            continue
        pk = eclib.lift_x(int.from_bytes(pub, "big"))
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        rp = eclib.lift_x(r) if r < eclib.P else None
        if pk is None or rp is None or s >= eclib.N:
            batch.ok.append(False)
            continue
        batch.pxn.append(pk[0].to_bytes(32, "big"))
        batch.pyn.append((eclib.P - pk[1]).to_bytes(32, "big"))
        batch.rxn.append(rp[0].to_bytes(32, "big"))
        batch.ryn.append((eclib.P - rp[1]).to_bytes(32, "big"))
        batch.s.append(s)
        batch.e.append(schnorr_challenge(sig[:32], pub, msg))
        batch.ok.append(True)
    return batch


def _aggregate_weights(items: list) -> list:
    """Deterministic per-signature random weights a_i, seeded from the
    batch transcript: ChaCha20 keystream keyed by the SHA256 of every
    (pub, msg, sig) in order.  An attacker committing to signatures before
    knowing the weights cannot craft errors that cancel (the falsification
    test pins exactly this).  a_i == 0 is remapped to 1 so every live lane
    stays coupled to the combined check."""
    from kaspa_tpu.crypto import chacha

    h = hashlib.sha256(b"kaspa-tpu/aggregate-weights/v1")
    for pub, msg, sig in items:
        for part in (pub, msg, sig):
            h.update(len(part).to_bytes(4, "little"))
            h.update(part)
    seed = np.frombuffer(h.digest(), dtype=np.uint8)[None, :]
    stream = chacha.keystream(seed, _AGG_WEIGHT_BYTES * len(items))[0].tobytes()
    return [
        int.from_bytes(stream[i * _AGG_WEIGHT_BYTES : (i + 1) * _AGG_WEIGHT_BYTES], "big") or 1
        for i in range(len(items))
    ]


def _aggregate_args(prep: _AggBatch, weights: list, rows: dict, idxs: list):
    """Marshal the selected live lanes into bucket-padded device arrays.

    rows[i] is item i's position in prep's compacted columns.  Pad lanes
    are all-zero: digit 0 selects the true-identity table entry, so they
    contribute nothing to any window sum.
    """
    from kaspa_tpu.ops.secp256k1 import aggregate as agg

    b = _bucket(len(idxs))
    cs = [weights[i] * prep.e[rows[i]] % eclib.N for i in idxs]
    u = 0
    for i in idxs:
        u += weights[i] * prep.s[rows[i]]
    ws = [weights[i] for i in idxs]
    c_digits = _scalars_to_digits(cs, b)
    # 128-bit weights: digit columns 0..31 are statically zero, ship only
    # the live half so the kernel skips half the R-side gathers/adds
    a_digits = _scalars_to_digits(ws, b)[:, agg.A_WINDOWS :]
    u_digits = pt.scalar_digits_msb(u % eclib.N)
    sel = lambda col: [col[rows[i]] for i in idxs]  # noqa: E731
    return (
        _be32_to_limbs(sel(prep.pxn), b),
        _be32_to_limbs(sel(prep.pyn), b),
        _be32_to_limbs(sel(prep.rxn), b),
        _be32_to_limbs(sel(prep.ryn), b),
        c_digits,
        a_digits,
        u_digits,
    ), b


def _run_aggregate_shape(b: int, args) -> bool:
    """One aggregate device dispatch with the same compile bookkeeping as
    _Batch.run: jit_compile span + warm-manifest entry (family aggregate)
    on the first sight of a bucket, shape discarded if the compile dies."""
    from kaspa_tpu.ops.secp256k1 import aggregate as agg

    shape_key = (_AGG_KERNEL_NAME, b)
    new_shape = shape_key not in _seen_shapes
    if new_shape:
        _seen_shapes.add(shape_key)
        _NEW_SHAPES.inc(_AGG_KERNEL_NAME)
        try:
            with trace.span("secp.jit_compile", kernel=_AGG_KERNEL_NAME, bucket=b):
                FAULTS.fire("device.jit_compile")
                ok = agg.aggregate_check(*args)
        except BaseException:
            _seen_shapes.discard(shape_key)
            raise
        supervisor.note_shape(_AGG_KERNEL_NAME, b, family="aggregate")
        return ok
    return agg.aggregate_check(*args)


def _aggregate_device_check(prep: _AggBatch, weights: list, rows: list, idxs: list):
    """Guarded combined check for one lane subset: True / False, or None
    when the device is unavailable (breaker open, hang, dispatch error) —
    the caller then routes the subset to the host degraded lane."""
    FAULTS.fire("device.verify")
    FAULTS.fire("device.hang")
    n = len(idxs)
    args, b = _aggregate_args(prep, weights, rows, idxs)
    _AGG_CHECKS.inc()
    _BATCH_SIZE.observe(n)
    _OCCUPANCY.observe(100.0 * n / b)
    _PADDED_LANES.inc(b - n)
    br = device_breaker()
    if not br.allow():
        return None
    tier = "dispatch" if (_AGG_KERNEL_NAME, b) in _seen_shapes else "compile"
    try:
        with trace.span("secp.device_dispatch", kernel=_AGG_KERNEL_NAME, batch=n, bucket=b):
            ok = supervisor.run_supervised(
                lambda: _run_aggregate_shape(b, args),
                tier=tier,
                kernel=_AGG_KERNEL_NAME,
                jobs=n,
            )
    except supervisor.DeviceHangError:
        br.record_failure(cause=HUNG)
        supervisor.note_requeue(n)
        return None
    except Exception:  # noqa: BLE001 - device boundary: any failure trips
        br.record_failure()
        return None
    br.record_success()
    return bool(ok)


def _resolve_aggregate(prep, weights, rows, idxs, mask, items) -> None:
    """Recursive bisection to the exact mask.  A passing combined check
    proves every lane in the subset; a failing one splits in half (both
    halves re-aggregated under the SAME top-level weights, so one bad
    signature keeps failing every superset it lands in); subsets at or
    below the leaf size resolve per-signature on the ladder path."""
    if not idxs:
        return
    if len(idxs) <= _AGG_LEAF:
        _AGG_LEAF_JOBS.inc(len(idxs))
        sub_mask = schnorr_verify_batch([items[i] for i in idxs])
        for k, i in enumerate(idxs):
            mask[i] = bool(sub_mask[k])
        return
    # warm-bucket discipline: a subset that would pad into a never-compiled
    # bucket splits at the largest warm one instead (each chunk is its own
    # sound sub-aggregate), exactly like _Batch.run's cold-split
    b = _bucket(len(idxs))
    if (
        (_AGG_KERNEL_NAME, b) not in _seen_shapes
        and _cold_split_enabled()
        and not getattr(_force_tls, "on", False)
    ):
        warm = max(
            (bk for k, bk in _seen_shapes if k == _AGG_KERNEL_NAME and bk < b),
            default=None,
        )
        if warm is not None and warm < len(idxs):
            _COLD_SPLITS.inc(_AGG_KERNEL_NAME)
            for off in range(0, len(idxs), warm):
                _resolve_aggregate(
                    prep, weights, rows, idxs[off : off + warm], mask, items
                )
            return
    ok = _aggregate_device_check(prep, weights, rows, idxs)
    if ok is True:
        for i in idxs:
            mask[i] = True
        return
    if ok is None:
        _AGG_FALLBACK_JOBS.inc(len(idxs))
        sub_mask = host_verify_batch("schnorr", [items[i] for i in idxs])
        for k, i in enumerate(idxs):
            mask[i] = bool(sub_mask[k])
        return
    _AGG_BISECT_STEPS.inc()
    half = len(idxs) // 2
    _resolve_aggregate(prep, weights, rows, idxs[:half], mask, items)
    _resolve_aggregate(prep, weights, rows, idxs[half:], mask, items)


def schnorr_verify_batch_aggregate(items) -> np.ndarray:
    """Aggregate-mode schnorr verify: bit-identical mask contract to
    schnorr_verify_batch, one multi-scalar device pass in the common
    (all-valid) case.  items: iterable of (pubkey32, msg32, sig64)."""
    items = list(items)
    n = len(items)
    if n == 0:
        return np.zeros(0, dtype=bool)
    with trace.span("dispatch.aggregate", jobs=n):
        with trace.span("secp.host_marshal", kernel=_AGG_KERNEL_NAME, batch=n):
            prep = _build_schnorr_aggregate(items)
            weights = _aggregate_weights(items)
        _AGG_BATCHES.inc()
        _AGG_JOBS.inc(n)
        mask = np.zeros(n, dtype=bool)
        # rows maps item index -> position in prep's compacted columns
        # (precheck failures occupy no column and stay False in the mask)
        rows, idxs, live = {}, [], 0
        for i, ok in enumerate(prep.ok):
            if ok:
                rows[i] = live
                idxs.append(i)
                live += 1
        _resolve_aggregate(prep, weights, rows, idxs, mask, items)
    return mask


def _build_ecdsa_batch(items: list) -> _Batch:
    batch = _Batch()
    half_n = eclib.N // 2
    for pub, msg, sig in items:
        if len(sig) != 64 or len(msg) != 32:
            batch.push_invalid()
            continue
        pk = eclib.parse_compressed(pub)
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if pk is None or not (1 <= r < eclib.N) or not (1 <= s < eclib.N) or s > half_n:
            batch.push_invalid()
            continue
        z = int.from_bytes(msg, "big") % eclib.N
        si = pow(s, -1, eclib.N)
        u1 = z * si % eclib.N
        u2 = r * si % eclib.N
        batch.push(pk[0], pk[1], r, u1, u2)
    return batch


def ecdsa_verify_batch(items) -> np.ndarray:
    """items: iterable of (pubkey33, msg32, sig64_compact) -> bool mask."""
    items = list(items)
    return _run_guarded(_build_ecdsa_batch(items), ecdsa_verify, items, eclib.ecdsa_verify)


def verify_batch(kind: str, items) -> np.ndarray:
    """Kind-dispatching batched verify ("schnorr" | "ecdsa") — the entry
    the verify fabric's slice workers, the coalescing dispatcher, and the
    legacy synchronous txscript lane all route through.  Schnorr batches
    honor the process-wide verify mode (`ops.dispatch.set_verify_mode`):
    the aggregate RLC lane when selected (or when "auto" says the batch
    is past the measured crossover), the per-signature ladder otherwise —
    masks are bit-identical either way."""
    items = list(items)
    if kind == "schnorr":
        from kaspa_tpu.ops import dispatch as dispatch_mod  # deferred: import DAG

        if dispatch_mod.resolve_verify_mode(kind, len(items)) == "aggregate":
            return schnorr_verify_batch_aggregate(items)
        return schnorr_verify_batch(items)
    return ecdsa_verify_batch(items)


def host_verify_batch(kind: str, items) -> np.ndarray:
    """Host-only verify for one super-batch: the same precheck + eclib
    oracle lane the breaker-open path runs, callable directly.  This is
    the fabric balancer's final failover tier — every slice dead or hung
    still yields bit-identical acceptance decisions, just at host
    throughput, and it can never touch a (possibly wedged) device."""
    items = list(items)
    if kind == "schnorr":
        return _host_lane(_build_schnorr_batch(items), "schnorr_verify", items, eclib.schnorr_verify)
    return _host_lane(_build_ecdsa_batch(items), "ecdsa_verify", items, eclib.ecdsa_verify)


# --- supervision hooks ----------------------------------------------------

_CANARY_SECKEY = int.from_bytes(hashlib.sha256(b"kaspa-tpu canary").digest(), "big") % eclib.N or 1


def _canary_items(count: int = 2) -> list:
    """Tiny known-answer workload (fixed key, distinct messages): every
    signature is valid, so a canary dispatch must return an all-True mask."""
    pub = eclib.schnorr_pubkey(_CANARY_SECKEY)
    out = []
    for i in range(count):
        msg = hashlib.sha256(b"canary-msg-%d" % i).digest()
        out.append((pub, msg, eclib.schnorr_sign(msg, _CANARY_SECKEY)))
    return out


def canary_probe() -> bool:
    """One supervised device dispatch of the known-answer batch — the
    prober's HALF_OPEN probe.  Bypasses the breaker gate (the prober holds
    the probe slot) and runs with fault injection suppressed so drills
    keep their requeued==injected accounting.  True iff the device
    answered correctly within the watchdog deadline."""
    from kaspa_tpu.resilience import faults as faults_mod

    items = _canary_items()
    batch = _build_schnorr_batch(items)

    def _dispatch():
        with faults_mod.suppress():
            return batch.run(schnorr_verify)

    mask = supervisor.run_supervised(
        _dispatch,
        tier=_dispatch_tier(schnorr_verify, len(items)),
        kernel="schnorr_verify",
        jobs=len(items),
    )
    return bool(np.asarray(mask).all())


_PRETRACE_KERNELS = {"schnorr_verify": schnorr_verify, "ecdsa_verify": ecdsa_verify}


def _pretrace_aggregate_bucket(bucket: int) -> str:
    """Aggregate-family pretrace: compile the multi-scalar partials +
    finish kernels at one bucket shape with an all-zero (identity-summing)
    batch, under the compile-tier watchdog."""
    if (_AGG_KERNEL_NAME, bucket) in _seen_shapes:
        return "warm"
    zeros32 = [_ZERO32] * bucket
    args = (
        _be32_to_limbs(zeros32, bucket),
        _be32_to_limbs(zeros32, bucket),
        _be32_to_limbs(zeros32, bucket),
        _be32_to_limbs(zeros32, bucket),
        _scalars_to_digits([0] * bucket, bucket),
        _scalars_to_digits([0] * bucket, bucket)[:, 32:],
        pt.scalar_digits_msb(0),
    )

    def _dispatch():
        from kaspa_tpu.resilience import faults as faults_mod

        _force_tls.on = True
        try:
            with faults_mod.suppress():
                return _run_aggregate_shape(bucket, args)
        finally:
            _force_tls.on = False

    try:
        supervisor.run_supervised(_dispatch, tier="compile", kernel=_AGG_KERNEL_NAME, jobs=bucket)
    except Exception as e:  # noqa: BLE001 - pretrace is best-effort
        return f"error:{type(e).__name__}"
    return "traced"


def pretrace_bucket(kernel_name: str, bucket: int) -> str:
    """Compile one (kernel, bucket) shape ahead of traffic (warm-manifest
    restart path).  Dispatches an all-invalid batch of exactly ``bucket``
    jobs with the warm-split bypassed so the target shape itself compiles;
    runs under the watchdog's compile tier.  Returns "warm" (already
    compiled this process), "traced", or "error:...".
    """
    if kernel_name == _AGG_KERNEL_NAME:
        return _pretrace_aggregate_bucket(bucket) if bucket >= 8 else f"error:unknown {kernel_name}/{bucket}"
    if kernel_name == "muhash_tree":
        from kaspa_tpu.ops import muhash_ops

        def _dispatch():
            return muhash_ops.pretrace_bucket(bucket)

        try:
            return supervisor.run_supervised(_dispatch, tier="compile", kernel=kernel_name, jobs=bucket)
        except Exception as e:  # noqa: BLE001 - pretrace is best-effort
            return f"error:{type(e).__name__}"
    kernel = _PRETRACE_KERNELS.get(kernel_name)
    if kernel is None or bucket < 8:
        return f"error:unknown {kernel_name}/{bucket}"
    if (kernel_name, bucket) in _seen_shapes:
        return "warm"
    batch = _Batch()
    for _ in range(bucket):
        batch.push_invalid()

    def _dispatch():
        from kaspa_tpu.resilience import faults as faults_mod

        _force_tls.on = True
        try:
            with faults_mod.suppress():
                return batch.run(kernel)
        finally:
            _force_tls.on = False

    try:
        supervisor.run_supervised(_dispatch, tier="compile", kernel=kernel_name, jobs=bucket)
    except Exception as e:  # noqa: BLE001 - pretrace is best-effort
        return f"error:{type(e).__name__}"
    return "traced"
