"""Pure-python BLAKE3 (keyed mode) — host-side, spec implementation.

Needed for the reference's blake3-keyed domain hashers
(crypto/hashes/src/hashers.rs:39-55,120-151): v1 transaction ids and the
KIP-21 SeqCommit commitments.  Keys are the domain string zero-padded to 32
bytes.  One-shot oriented (consensus preimages are small); a batched JAX
kernel can replace the compression loop if SeqCommit volume ever warrants.
"""

from __future__ import annotations

import struct

_IV = (0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A, 0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19)
_PERM = (2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8)

CHUNK_START = 1
CHUNK_END = 2
PARENT = 4
ROOT = 8
KEYED_HASH = 16

_CHUNK_LEN = 1024
_BLOCK_LEN = 64
_M32 = 0xFFFFFFFF


def _rotr(x, n):
    return ((x >> n) | (x << (32 - n))) & _M32


def _compress(cv, block_words, counter, block_len, flags):
    v = list(cv) + [_IV[0], _IV[1], _IV[2], _IV[3], counter & _M32, (counter >> 32) & _M32, block_len, flags]
    m = list(block_words)

    def g(a, b, c, d, mx, my):
        v[a] = (v[a] + v[b] + mx) & _M32
        v[d] = _rotr(v[d] ^ v[a], 16)
        v[c] = (v[c] + v[d]) & _M32
        v[b] = _rotr(v[b] ^ v[c], 12)
        v[a] = (v[a] + v[b] + my) & _M32
        v[d] = _rotr(v[d] ^ v[a], 8)
        v[c] = (v[c] + v[d]) & _M32
        v[b] = _rotr(v[b] ^ v[c], 7)

    for r in range(7):
        g(0, 4, 8, 12, m[0], m[1])
        g(1, 5, 9, 13, m[2], m[3])
        g(2, 6, 10, 14, m[4], m[5])
        g(3, 7, 11, 15, m[6], m[7])
        g(0, 5, 10, 15, m[8], m[9])
        g(1, 6, 11, 12, m[10], m[11])
        g(2, 7, 8, 13, m[12], m[13])
        g(3, 4, 9, 14, m[14], m[15])
        if r < 6:
            m = [m[_PERM[i]] for i in range(16)]

    return [(v[i] ^ v[i + 8]) & _M32 for i in range(8)] + [(v[i + 8] ^ cv[i]) & _M32 for i in range(8)]


def _words(block: bytes):
    return struct.unpack("<16I", block.ljust(64, b"\x00"))


def _chunk_cv(key_words, chunk: bytes, chunk_index: int, base_flags: int, is_root: bool):
    blocks = [chunk[i : i + _BLOCK_LEN] for i in range(0, len(chunk), _BLOCK_LEN)] or [b""]
    cv = list(key_words)
    for bi, block in enumerate(blocks):
        flags = base_flags
        if bi == 0:
            flags |= CHUNK_START
        if bi == len(blocks) - 1:
            flags |= CHUNK_END
            if is_root:
                flags |= ROOT
        cv = _compress(cv, _words(block), chunk_index, len(block), flags)[:8]
    return cv


def blake3_keyed(key32: bytes, data: bytes) -> bytes:
    """BLAKE3 keyed hash, 32-byte output."""
    assert len(key32) == 32
    key_words = struct.unpack("<8I", key32)
    base = KEYED_HASH
    chunks = [data[i : i + _CHUNK_LEN] for i in range(0, len(data), _CHUNK_LEN)] or [b""]
    if len(chunks) == 1:
        cv = _chunk_cv(key_words, chunks[0], 0, base, is_root=True)
        return struct.pack("<8I", *cv)
    cvs = [_chunk_cv(key_words, c, i, base, is_root=False) for i, c in enumerate(chunks)]
    # left-complete binary tree: combine adjacent pairs, odd tail carries up
    while len(cvs) > 2:
        nxt = [
            _compress(key_words, tuple(cvs[i] + cvs[i + 1]), 0, _BLOCK_LEN, base | PARENT)[:8]
            for i in range(0, len(cvs) - 1, 2)
        ]
        if len(cvs) % 2:
            nxt.append(cvs[-1])
        cvs = nxt
    root = _compress(key_words, tuple(cvs[0] + cvs[1]), 0, _BLOCK_LEN, base | PARENT | ROOT)[:8]
    return struct.pack("<8I", *root)


def blake3(data: bytes) -> bytes:
    """Plain (unkeyed) BLAKE3, 32-byte output: the standard IV as the key
    words and no KEYED_HASH flag (used by OpBlake3, opcodes/mod.rs:1656)."""
    chunks = [data[i : i + _CHUNK_LEN] for i in range(0, len(data), _CHUNK_LEN)] or [b""]
    if len(chunks) == 1:
        cv = _chunk_cv(_IV, chunks[0], 0, 0, is_root=True)
        return struct.pack("<8I", *cv)
    cvs = [_chunk_cv(_IV, c, i, 0, is_root=False) for i, c in enumerate(chunks)]
    while len(cvs) > 2:
        nxt = [
            _compress(_IV, tuple(cvs[i] + cvs[i + 1]), 0, _BLOCK_LEN, PARENT)[:8]
            for i in range(0, len(cvs) - 1, 2)
        ]
        if len(cvs) % 2:
            nxt.append(cvs[-1])
        cvs = nxt
    root = _compress(_IV, tuple(cvs[0] + cvs[1]), 0, _BLOCK_LEN, PARENT | ROOT)[:8]
    return struct.pack("<8I", *root)


def domain_key(domain: bytes) -> bytes:
    assert len(domain) <= 32
    return domain.ljust(32, b"\x00")


def keyed_hash(domain: bytes, data: bytes) -> bytes:
    return blake3_keyed(domain_key(domain), data)


class Blake3Keyed:
    """Incremental facade (buffers; compresses on digest)."""

    def __init__(self, domain: bytes):
        self._key = domain_key(domain)
        self._buf = bytearray()

    def update(self, data: bytes):
        self._buf += data
        return self

    def digest(self) -> bytes:
        return blake3_keyed(self._key, bytes(self._buf))


PAYLOAD_ZERO_DIGEST = keyed_hash(b"PayloadDigest", b"")
