"""Structured logging for the node (core/src/log/{logger,appender}.rs).

The reference layers env_logger-style filtering with per-subsystem
targets, console + rotating file appenders.  Here: thin wrappers over the
stdlib logging module with the same shape — `kaspa.<subsystem>` logger
tree, one console handler, optional file appender, and an env filter
(KASPA_TPU_LOG, e.g. "info" or "debug,consensus=trace")."""

from __future__ import annotations

import logging
import os
import sys

_LEVELS = {
    "off": logging.CRITICAL + 10,
    "error": logging.ERROR,
    "warn": logging.WARNING,
    "info": logging.INFO,
    "debug": logging.DEBUG,
    "trace": 5,
}
logging.addLevelName(5, "TRACE")

_FORMAT = "%(asctime)s [%(levelname)-5s] %(name)s: %(message)s"
_root = logging.getLogger("kaspa")
_configured = False


class _KaspaLogger(logging.LoggerAdapter):
    def trace(self, msg, *args, **kwargs):
        self.log(5, msg, *args, **kwargs)

    def warn(self, msg, *args, **kwargs):  # reference naming
        self.warning(msg, *args, **kwargs)

    def exception(self, msg, *args, **kwargs):
        self.logger.exception(msg, *args, **kwargs)


def init_logger(spec: str | None = None, log_file: str | None = None) -> None:
    """Configure once from a filter spec: "<default>[,<subsystem>=<level>...]".

    Mirrors the reference's logger::init_logger(filters) semantics."""
    global _configured
    spec = spec if spec is not None else os.environ.get("KASPA_TPU_LOG", "info")
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    default = "info"
    per_target: dict[str, str] = {}
    for p in parts:
        if "=" in p:
            target, lvl = p.split("=", 1)
            per_target[target.strip()] = lvl.strip()
        else:
            default = p
    _root.setLevel(_LEVELS.get(default, logging.INFO))
    for target, lvl in per_target.items():
        logging.getLogger(f"kaspa.{target}").setLevel(_LEVELS.get(lvl, logging.INFO))
    if not _configured:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        _root.addHandler(handler)
        _root.propagate = False
        _configured = True
    if log_file:
        fh = logging.FileHandler(log_file)
        fh.setFormatter(logging.Formatter(_FORMAT))
        _root.addHandler(fh)


def get_logger(subsystem: str) -> _KaspaLogger:
    if not _configured:
        init_logger()
    return _KaspaLogger(logging.getLogger(f"kaspa.{subsystem}"), {})
