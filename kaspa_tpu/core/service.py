"""Core/Service runtime: ordered service lifecycle for the node assembly.

Reference: core/src/{core.rs,service.rs,signals.rs} — services register
with a Core, which starts them in bind order (each returning its worker
threads), joins them, and shuts them down in reverse order.  SIGINT/
SIGTERM trip the shutdown path exactly once.
"""

from __future__ import annotations

import signal
import threading

from kaspa_tpu.utils.sync import ranked_lock

from kaspa_tpu.core.log import get_logger

log = get_logger("core")


class Service:
    """Service trait (service.rs): subclass or duck-type.

    - ``ident()``  — stable name for lookup/logging
    - ``start(core)`` — begin work; return a list of threads the core joins
    - ``stop()``  — signal termination; must be idempotent
    """

    def ident(self) -> str:
        return type(self).__name__

    def start(self, core: "Core") -> list[threading.Thread]:
        return []

    def stop(self) -> None:
        pass


class CallbackService(Service):
    """Adapter for wiring existing objects into the Core without
    inheritance (most of our subsystems predate the runtime)."""

    def __init__(self, ident: str, on_start=None, on_stop=None):
        self._ident = ident
        self._on_start = on_start
        self._on_stop = on_stop

    def ident(self) -> str:
        return self._ident

    def start(self, core: "Core") -> list[threading.Thread]:
        if self._on_start is not None:
            return self._on_start(core) or []
        return []

    def stop(self) -> None:
        if self._on_stop is not None:
            self._on_stop()


class Core:
    """core.rs Core: bind -> start -> join; shutdown stops services in
    reverse bind order (dependents before dependencies)."""

    def __init__(self):
        self.keep_running = threading.Event()
        self.keep_running.set()
        self._services: list[Service] = []
        self._workers: list[threading.Thread] = []
        self._mu = ranked_lock("service.list")
        self._shutdown_once = threading.Event()
        self._shutdown_mu = ranked_lock("service.shutdown")

    def bind(self, service: Service) -> None:
        with self._mu:
            self._services.append(service)

    def find(self, ident: str) -> Service | None:
        with self._mu:
            for s in self._services:
                if s.ident() == ident:
                    return s
        return None

    def start(self) -> list[threading.Thread]:
        with self._mu:
            services = list(self._services)
        workers: list[threading.Thread] = []
        for service in services:
            ws = service.start(self)
            log.debug("service %s started (%d workers)", service.ident(), len(ws))
            workers.extend(ws)
        self._workers = workers
        log.info("core started %d services, %d workers", len(services), len(workers))
        return workers

    def join(self, workers: list[threading.Thread] | None = None, timeout: float | None = None) -> None:
        for w in workers if workers is not None else self._workers:
            w.join(timeout)

    def wait_for_shutdown(self, timeout: float | None = None) -> bool:
        """Block until shutdown() trips (the inverse of keep_running)."""
        return self._shutdown_once.wait(timeout)

    def run(self) -> None:
        """start + block until shutdown() trips (services stop there)."""
        self.start()
        self.wait_for_shutdown()

    def shutdown(self) -> None:
        """Idempotent: stops services in reverse bind order exactly once.
        Late callers block until the in-flight stop completes, so code
        sequenced after shutdown() can rely on services being down."""
        with self._shutdown_mu:
            if self._shutdown_once.is_set():
                return
            self.keep_running.clear()
            self._stop_services()
            self._shutdown_once.set()

    def _stop_services(self) -> None:
        with self._mu:
            services = list(reversed(self._services))
        for service in services:
            try:
                service.stop()
                log.debug("service %s stopped", service.ident())
            except Exception:  # one failing stop must not strand the rest
                log.exception("service %s failed to stop", service.ident())

    def install_signal_handlers(self) -> None:
        """signals.rs Signals::init: first signal begins shutdown; a second
        forces exit (only callable from the main thread)."""

        def handler(signum, _frame):
            if self._shutdown_once.is_set():
                log.warn("second signal %s: forcing exit", signum)
                raise SystemExit(1)
            log.info("signal %s: shutting down", signum)
            threading.Thread(target=self.shutdown, daemon=True).start()

        signal.signal(signal.SIGINT, handler)
        signal.signal(signal.SIGTERM, handler)
