"""Tick service: cancellable periodic tasks (core/src/task/tick.rs).

Periodic workers (perf monitor sampling, mempool expiry scans, template
rebuilds) register a callback + interval; shutdown wakes every sleeper
immediately instead of waiting out the interval."""

from __future__ import annotations

import threading

from kaspa_tpu.core.service import Service


class TickService(Service):
    def __init__(self):
        self._stop = threading.Event()
        self._tasks: list[tuple[float, object]] = []

    def ident(self) -> str:
        return "tick-service"

    def register(self, interval_s: float, callback) -> None:
        self._tasks.append((interval_s, callback))

    def start(self, core) -> list[threading.Thread]:
        threads = []
        for interval, callback in self._tasks:
            t = threading.Thread(target=self._loop, args=(interval, callback), daemon=True)
            t.start()
            threads.append(t)
        return threads

    def _loop(self, interval: float, callback) -> None:
        while not self._stop.wait(interval):
            try:
                callback()
            except Exception:
                from kaspa_tpu.core.log import get_logger

                get_logger("tick").exception("periodic task failed")

    def stop(self) -> None:
        self._stop.set()
