from kaspa_tpu.core.service import Core, Service
from kaspa_tpu.core.tick import TickService
