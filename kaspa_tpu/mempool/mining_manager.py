"""MiningManager: the mempool facade + block-template pipeline.

Reference: mining/src/manager.rs (validate_and_insert_transaction,
get_block_template with cache, handle_new_block_transactions) and
mining/src/block_template/builder.rs.  Tx validation against the virtual
UTXO view routes through the consensus validator (scripts batched on
device); templates come from Consensus.build_block_template.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.model import Transaction
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.consensus.processes.coinbase import MinerData
from kaspa_tpu.consensus.processes.transaction_validator import TxRuleError
from kaspa_tpu.mempool.mempool import Mempool, MempoolConfig, MempoolError, MempoolTx
from kaspa_tpu.observability.core import REGISTRY

_TEMPLATE_REBUILD_MS = REGISTRY.histogram(
    "mempool_template_rebuild_ms",
    (0.5, 1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0),
    help="block-template rebuild latency (frontier selection + build), milliseconds",
)
from kaspa_tpu.observability.shed import SHED as _SHED  # noqa: E402  (family declared once there)


@dataclass
class TemplateCache:
    """block_template cache (mining/src/cache.rs): short-lived reuse window.

    Tx-intake invalidation can be *debounced*: a new pool entry makes the
    cached template stale-but-still-mineable (it just misses the newest
    txs), so ``mark_dirty`` keeps serving it until ``debounce`` seconds
    after the last rebuild — a tx flood then costs one rebuild per debounce
    window instead of one per transaction.  The default debounce of 0 keeps
    the historical rebuild-on-next-request behavior; the daemon and the
    tx-flood harness opt in.  Block acceptance calls ``clear`` (the cached
    template may now be *invalid*), which drops it unconditionally.
    """

    template: Block | None = None
    created: float = 0.0
    lifetime: float = 1.0  # seconds
    debounce: float = 0.0  # min seconds between tx-churn-driven rebuilds
    dirty: bool = False
    # CRITICAL-brownout deferral: extra grace past lifetime/debounce during
    # which a stale-but-mineable template keeps serving instead of paying a
    # rebuild (bounded staleness: hard ceiling lifetime + defer_grace).
    # clear() is unaffected — an *invalid* template never survives.
    defer_grace: float = 0.0

    def get(self):
        if self.template is None:
            return None
        age = time.monotonic() - self.created
        if age >= self.lifetime + self.defer_grace:
            return None
        if age >= self.lifetime or (self.dirty and age >= self.debounce):
            if self.defer_grace > 0.0:
                _SHED.inc("template_deferral")
                return self.template
            return None
        return self.template

    def set(self, template: Block):
        self.template = template
        self.created = time.monotonic()
        self.dirty = False

    def mark_dirty(self):
        self.dirty = True

    def clear(self):
        self.template = None
        self.dirty = False


@dataclass
class PreparedTx:
    """One entrant past the contextual pre-checks, its signature/script jobs
    collected into a shared checker, awaiting the batched verify verdict.
    ``entry is None`` means the tx was parked in the orphan pool during
    prepare (missing inputs) and needs no finish step."""

    tx: Transaction
    token: int
    entry: MempoolTx | None

    @property
    def orphan(self) -> bool:
        return self.entry is None


class MiningManager:
    def __init__(
        self,
        consensus: Consensus,
        config: MempoolConfig | None = None,
        seed: int | None = None,
        template_debounce: float = 0.0,
    ):
        self.consensus = consensus
        params = consensus.params
        self.mempool = Mempool(
            config,
            target_time_per_block_seconds=params.target_time_per_block / 1000.0,
            seed=seed,
        )
        self.template_cache = TemplateCache(debounce=template_debounce)

    def set_template_deferral(self, grace_s: float) -> None:
        """Brownout seam: serve stale-but-mineable templates for up to
        ``grace_s`` past their normal rebuild point (0 restores normal
        rebuild behavior).  Block acceptance still clears unconditionally."""
        self.template_cache.defer_grace = max(0.0, float(grace_s))

    # --- fee estimation (manager.rs get_realtime_feerate_estimations) ---

    def get_fee_estimate(self):
        from kaspa_tpu.mempool.feerate import FeerateEstimatorArgs

        params = self.consensus.params
        args = FeerateEstimatorArgs(
            network_blocks_per_second=max(1, round(1000 / params.target_time_per_block)),
            maximum_mass_per_block=params.max_block_mass,
        )
        estimator = self.mempool.build_feerate_estimator(args)
        return estimator.calc_estimations(minimum_standard_feerate=1.0)

    # --- tx intake (manager.rs:296-421) ---

    def validate_and_insert_transaction(self, tx: Transaction) -> list[bytes]:
        """Validate against the virtual UTXO view and insert; returns RBF-evicted
        txids.  Raises MempoolError/TxRuleError on rejection; parks txs with
        missing inputs in the orphan pool.

        The batched ingest tier (kaspa_tpu/ingest/) runs the same two
        halves — ``prepare_transaction`` per entrant in arrival order, one
        shared checker dispatch, then ``finish_transaction`` in the same
        order — so batched admission is state-identical to this per-tx path.
        """
        checker = self.consensus.transaction_validator.new_checker()
        prepared = self.prepare_transaction(tx, checker, token=0)
        err = checker.dispatch().get(0)
        return self.finish_transaction(prepared, err)

    def prepare_transaction(self, tx: Transaction, checker, token: int) -> PreparedTx:
        """Contextual pre-checks + signature-job collection for one entrant.

        Runs everything that must see mempool/consensus state in arrival
        order: isolation + gas-cap + header-context checks, the virtual
        UTXO view lookup (missing inputs park the tx in the orphan pool
        immediately), and fee/mass population — collecting the tx's
        signature/script jobs into ``checker`` under ``token`` instead of
        verifying inline.  Raises MempoolError/TxRuleError on pre-check
        rejection."""
        validator = self.consensus.transaction_validator
        validator.validate_tx_in_isolation(tx)
        # per-tx gas cap (mining/src/mempool/check_transaction_limits.rs:19
        # RejectGas): a tx whose gas alone exceeds the per-lane cap can never
        # be mined, so it must not enter the pool
        if tx.gas > self.consensus.params.gas_per_lane:
            raise MempoolError(
                f"transaction gas {tx.gas} exceeds the per-lane cap {self.consensus.params.gas_per_lane}",
                code="tx-gas",
            )
        virtual = self.consensus.virtual_state
        validator.validate_tx_in_header_context(tx, virtual.daa_score, virtual.past_median_time)

        view = self.consensus.get_virtual_utxo_view()
        entries = []
        missing = False
        for inp in tx.inputs:
            entry = view.get(inp.previous_outpoint)
            if entry is None:
                missing = True
                break
            entries.append(entry)
        if missing:
            nc = self._masses(tx)
            entry = MempoolTx(tx, fee=0, mass=nc.compute_mass, added_daa_score=virtual.daa_score, transient_mass=nc.transient_mass)
            self.mempool.insert(entry, orphan=True)
            return PreparedTx(tx, token, None)

        accessor = None
        if self.consensus.params.toccata_active(virtual.daa_score):
            # mempool/consensus acceptance parity for OpChainblockSeqCommit
            # (validate_block_template_transaction passes the same accessor)
            from kaspa_tpu.consensus.smt_processor import ConsensusSeqCommitAccessor

            accessor = ConsensusSeqCommitAccessor(
                self.consensus.sink(),
                self.consensus.reachability,
                self.consensus.storage.headers,
                self.consensus.params.toccata_active,
                self.consensus.params.finality_depth,
            )
        fee = validator.validate_populated_transaction_and_get_fee(
            tx, entries, virtual.daa_score, checker=checker, token=token, seq_commit_accessor=accessor
        )
        nc = self._masses(tx)
        return PreparedTx(
            tx, token, MempoolTx(tx, fee, nc.compute_mass, virtual.daa_score, nc.transient_mass)
        )

    def finish_transaction(self, prepared: PreparedTx, err) -> list[bytes]:
        """Second half of admission: consume the verify verdict for one
        prepared entrant and insert on success.  ``err`` is the checker's
        per-token result (None = all signatures/scripts valid)."""
        if prepared.entry is None:
            return []  # parked as orphan during prepare
        if err is not None:
            raise TxRuleError(str(err))
        evicted = self.mempool.insert(prepared.entry)
        self.template_cache.mark_dirty()
        return evicted

    def _masses(self, tx: Transaction):
        return self.consensus.transaction_validator.mass_calculator.calc_non_contextual_masses(tx)

    # --- block templates (manager.rs:94-215) ---

    def get_block_template(self, miner_data: MinerData, timestamp: int | None = None) -> Block:
        cached = self.template_cache.get()
        if cached is not None:
            return cached
        if timestamp is None:
            # real templates carry wall-clock time (clamped to pmt+1 by the
            # builder) — sync-state gating reads sink recency off these
            import time as _time

            timestamp = int(_time.time() * 1000)
        from kaspa_tpu.consensus.mass import BlockLaneLimits, BlockMassLimits

        params = self.consensus.params
        limits = BlockMassLimits.with_shared_limit(params.max_block_mass)
        lane_limits = BlockLaneLimits(params.lanes_per_block, params.gas_per_lane)
        t0 = time.perf_counter()
        selected = self.mempool.select_transactions(mass_limits=limits, lane_limits=lane_limits)
        template = self.consensus.build_block_template(miner_data, [e.tx for e in selected], timestamp)
        _TEMPLATE_REBUILD_MS.observe((time.perf_counter() - t0) * 1000.0)
        self.template_cache.set(template)
        return template

    # --- new-block notification (manager.rs:605 handle_new_block_transactions) ---

    def _notify_new_template(self) -> None:
        from kaspa_tpu.notify.notifier import Notification

        self.consensus.notification_root.notify(Notification("new-block-template", {}))

    def handle_new_block_transactions(self, block_txs: list[Transaction], daa_score: int) -> list[MempoolTx]:
        accepted_ids = [tx.id() for tx in block_txs]
        self.mempool.handle_accepted_transactions(accepted_ids, daa_score)
        spent = [inp.previous_outpoint for tx in block_txs for inp in tx.inputs]
        self.mempool.remove_conflicting(spent)
        self.mempool.expire(daa_score)
        self.template_cache.clear()
        # a fresh template is now available (notify/events.rs NewBlockTemplate)
        self._notify_new_template()
        # attempt to unorphan txs whose parents were just created
        return self.mempool.unorphan_candidates(set(accepted_ids))
