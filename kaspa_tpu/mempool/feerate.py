"""Fee estimation from mempool frontier weight.

Port of the reference's closed-form estimator (mining/src/feerate/mod.rs):
the mempool is modeled as an M/D/1-style queue where a transaction paying
feerate f waits `c1*c2/f^ALPHA + c1` seconds — c1 the amortized per-slot
inclusion interval, c2 the total frontier weight Σ (fee/mass)^ALPHA.  The
estimator inverts that curve at target waiting times (1 block / 1 min /
30 min / 1 h) and samples quantiles of the integral area so clients can
interpolate a full feerate-to-time function.
"""

from __future__ import annotations

from dataclasses import dataclass

ALPHA = 3


@dataclass(frozen=True)
class FeerateBucket:
    feerate: float
    estimated_seconds: float


@dataclass(frozen=True)
class FeerateEstimations:
    priority_bucket: FeerateBucket
    normal_buckets: list[FeerateBucket]
    low_buckets: list[FeerateBucket]

    def ordered_buckets(self) -> list[FeerateBucket]:
        return [self.priority_bucket, *self.normal_buckets, *self.low_buckets]


@dataclass(frozen=True)
class FeerateEstimatorArgs:
    network_blocks_per_second: int
    maximum_mass_per_block: int

    def network_mass_per_second(self) -> int:
        return self.network_blocks_per_second * self.maximum_mass_per_block


class FeerateEstimator:
    def __init__(self, total_weight: float, inclusion_interval: float, target_time_per_block_seconds: float):
        assert total_weight >= 0.0
        assert 0.0 <= inclusion_interval < 1.0
        self.total_weight = total_weight
        self.inclusion_interval = inclusion_interval
        self.target_time_per_block_seconds = target_time_per_block_seconds

    def feerate_to_time(self, feerate: float) -> float:
        c1, c2 = self.inclusion_interval, self.total_weight
        return c1 * c2 / feerate**ALPHA + c1

    def time_to_feerate(self, time: float) -> float:
        c1, c2 = self.inclusion_interval, self.total_weight
        assert c1 < time
        return ((c1 * c2 / time) / (1.0 - c1 / time)) ** (1.0 / ALPHA)

    def _antiderivative(self, feerate: float) -> float:
        c1, c2 = self.inclusion_interval, self.total_weight
        return c1 * c2 / (-2.0 * feerate ** (ALPHA - 1))

    def quantile(self, lower: float, upper: float, frac: float) -> float:
        """Feerate where the integral area reaches `frac` of [lower, upper]."""
        assert 0.0 <= frac <= 1.0
        if lower == upper:
            return lower
        assert 0.0 < lower <= upper
        c1, c2 = self.inclusion_interval, self.total_weight
        if c1 == 0.0 or c2 == 0.0:
            return lower
        z1 = self._antiderivative(lower)
        z2 = self._antiderivative(upper)
        z = frac * z2 + (1.0 - frac) * z1
        return ((c1 * c2) / (-2.0 * z)) ** (1.0 / (ALPHA - 1))

    def calc_estimations(self, minimum_standard_feerate: float) -> FeerateEstimations:
        minimum = minimum_standard_feerate
        # `high`: expected next-block inclusion
        high = max(self.time_to_feerate(self.target_time_per_block_seconds), minimum)
        # `low`: sub-hour AND at least the 0.25 quantile
        low = max(self.time_to_feerate(3600.0), self.quantile(minimum, high, 0.25))
        # `normal`: sub-minute AND at least the 0.66 quantile between low and high
        normal = max(self.time_to_feerate(60.0), self.quantile(low, high, 0.66))
        # an additional interpolation point between normal and low
        mid = max(self.time_to_feerate(1800.0), self.quantile(minimum, high, 0.5))
        return FeerateEstimations(
            priority_bucket=FeerateBucket(high, self.feerate_to_time(high)),
            normal_buckets=[
                FeerateBucket(normal, self.feerate_to_time(normal)),
                FeerateBucket(mid, self.feerate_to_time(mid)),
            ],
            low_buckets=[FeerateBucket(low, self.feerate_to_time(low))],
        )
