"""Mempool: pending-transaction pool with orphan handling and RBF.

Reference: mining/src/mempool/ (model/{pool,orphan_pool,frontier}.rs,
validate_and_insert_transaction.rs, replace_by_fee.rs,
handle_new_block_transactions.rs).  Template selection and fee estimation
ride the feerate frontier (mempool/frontier.py): ready transactions live in
a weight-augmented search tree; large frontiers are weight-sampled, small
ones greedily packed, and the closed-form feerate estimator is built from
the tree's weight prefix sums.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from kaspa_tpu.consensus.model import Transaction, TransactionOutpoint
from kaspa_tpu.mempool.feerate import FeerateEstimator, FeerateEstimatorArgs
from kaspa_tpu.mempool.frontier import FeerateKey, Frontier, LaneSelectionState


class MempoolError(Exception):
    """Mempool admission rejection.  ``code`` is a stable machine-readable
    identifier (the RPC layer forwards it verbatim so clients can branch
    without parsing prose): tx-duplicate, tx-double-spend, tx-rbf-rejected,
    tx-fee-too-low, mempool-full, tx-gas, tx-invalid, node-overloaded.
    ``retry_after_ms`` (node-overloaded only) is a resubmission hint the
    RPC layer forwards as ``retryAfterMs``."""

    def __init__(self, message: str, code: str = "tx-invalid", retry_after_ms: int | None = None):
        super().__init__(message)
        self.code = code
        self.retry_after_ms = retry_after_ms


@dataclass
class MempoolTx:
    tx: Transaction
    fee: int
    mass: int  # compute mass
    added_daa_score: int
    transient_mass: int = 0

    @property
    def storage_mass(self) -> int:
        return self.tx.storage_mass

    @property
    def feerate(self) -> float:
        return self.fee / max(self.mass, 1)


@dataclass
class MempoolConfig:
    maximum_transaction_count: int = 1_000_000
    maximum_orphan_transaction_count: int = 500
    transaction_expire_interval_daa_score: int = 60 * 10  # mempool/config.rs scale
    accepted_cache_size: int = 10_000
    allow_rbf: bool = True
    # feerate floor for pool entry (config.rs minimum_relay_transaction_fee);
    # 0.0 keeps the historical accept-everything behavior
    minimum_relay_feerate: float = 0.0


class Mempool:
    def __init__(
        self,
        config: MempoolConfig | None = None,
        target_time_per_block_seconds: float = 1.0,
        seed: int | None = None,
    ):
        self.config = config or MempoolConfig()
        self.pool: dict[bytes, MempoolTx] = {}  # txid -> entry
        self.outpoint_index: dict[TransactionOutpoint, bytes] = {}  # spent outpoint -> txid
        self.orphans: dict[bytes, MempoolTx] = {}
        self.accepted: dict[bytes, int] = {}  # txid -> daa score (LRU-ish)
        self.frontier = Frontier(target_time_per_block_seconds)
        self._children: dict[bytes, set[bytes]] = {}  # parent txid -> dependent txids
        # template-selection sampling RNG: seedable so SUSTAIN runs are
        # byte-reproducible (same seed -> identical weighted samples)
        self._rng = random.Random(0xD1CE if seed is None else seed)

    @staticmethod
    def _fkey(entry: MempoolTx) -> FeerateKey:
        return FeerateKey(
            entry.fee, max(entry.mass, 1), entry.tx.id(),
            lane=entry.tx.subnetwork_id, gas=entry.tx.gas,
        )

    def _is_ready(self, entry: MempoolTx) -> bool:
        """Ready = no in-pool ancestor (frontier membership criterion)."""
        return all(
            inp.previous_outpoint.transaction_id not in self.pool for inp in entry.tx.inputs
        )

    def __len__(self):
        return len(self.pool)

    def has(self, txid: bytes) -> bool:
        return txid in self.pool or txid in self.orphans

    def get(self, txid: bytes) -> MempoolTx | None:
        return self.pool.get(txid)

    # --- insertion (validate_and_insert_transaction.rs) ---

    def insert(self, entry: MempoolTx, orphan: bool = False) -> list[bytes]:
        """Insert a pre-validated tx.  Returns txids evicted by RBF.

        `orphan=True` parks the tx in the orphan pool (missing inputs).
        """
        txid = entry.tx.id()
        if self.has(txid) or txid in self.accepted:
            raise MempoolError(
                "transaction already in mempool or recently accepted", code="tx-duplicate"
            )
        if orphan:
            if len(self.orphans) >= self.config.maximum_orphan_transaction_count:
                # evict the lowest-feerate orphan (orphan_pool.rs limit policy)
                victim = min(self.orphans, key=lambda t: self.orphans[t].feerate)
                del self.orphans[victim]
            self.orphans[txid] = entry
            return []
        if len(self.pool) >= self.config.maximum_transaction_count:
            raise MempoolError("mempool is full", code="mempool-full")
        if entry.feerate < self.config.minimum_relay_feerate:
            raise MempoolError(
                f"transaction feerate {entry.feerate:.4f} below the minimum relay "
                f"feerate {self.config.minimum_relay_feerate:.4f}",
                code="tx-fee-too-low",
            )

        # double-spend / RBF (replace_by_fee.rs): a conflicting tx is replaced
        # only if the new one pays a strictly higher feerate than all conflicts
        conflicts = {self.outpoint_index[inp.previous_outpoint]
                     for inp in entry.tx.inputs if inp.previous_outpoint in self.outpoint_index}
        evicted = []
        if conflicts:
            if not self.config.allow_rbf:
                raise MempoolError(
                    "transaction double spends mempool transaction", code="tx-double-spend"
                )
            if any(self.pool[c].feerate >= entry.feerate for c in conflicts):
                raise MempoolError(
                    "replacement feerate not higher than conflicts", code="tx-rbf-rejected"
                )
            for c in conflicts:
                self._remove(c)
                evicted.append(c)

        self.pool[txid] = entry
        for inp in entry.tx.inputs:
            self.outpoint_index[inp.previous_outpoint] = txid
            parent = inp.previous_outpoint.transaction_id
            if parent in self.pool:
                self._children.setdefault(parent, set()).add(txid)
        if self._is_ready(entry):
            self.frontier.insert(self._fkey(entry))
        return evicted

    def _remove(self, txid: bytes, accepted: bool = False) -> None:
        """Remove a tx.  If it was `accepted` its chained dependents become
        ready (their inputs now live in the UTXO set) and join the frontier;
        otherwise the dependents are unredeemable and are removed too
        (remove_transaction with redeemers in the reference)."""
        entry = self.pool.pop(txid, None)
        if entry is None:
            return
        self.frontier.remove(self._fkey(entry))
        for inp in entry.tx.inputs:
            if self.outpoint_index.get(inp.previous_outpoint) == txid:
                del self.outpoint_index[inp.previous_outpoint]
            kids = self._children.get(inp.previous_outpoint.transaction_id)
            if kids is not None:
                kids.discard(txid)
        for child in list(self._children.pop(txid, ())):
            centry = self.pool.get(child)
            if centry is None:
                continue
            if accepted:
                if self._is_ready(centry):
                    self.frontier.insert(self._fkey(centry))
            else:
                self._remove(child, accepted=False)

    # --- new-block handling (handle_new_block_transactions.rs) ---

    def handle_accepted_transactions(self, accepted_txids: list[bytes], daa_score: int) -> None:
        for txid in accepted_txids:
            self._remove(txid, accepted=True)
            self.orphans.pop(txid, None)
            self.accepted[txid] = daa_score
        # bound the accepted cache
        if len(self.accepted) > self.config.accepted_cache_size:
            cutoff = sorted(self.accepted.values())[len(self.accepted) - self.config.accepted_cache_size]
            self.accepted = {t: s for t, s in self.accepted.items() if s >= cutoff}

    def remove_conflicting(self, spent_outpoints) -> list[bytes]:
        """Remove pool txs conflicting with outpoints spent by a new block."""
        removed = []
        for op in spent_outpoints:
            txid = self.outpoint_index.get(op)
            if txid is not None:
                self._remove(txid)
                removed.append(txid)
        return removed

    def expire(self, current_daa_score: int) -> list[bytes]:
        horizon = current_daa_score - self.config.transaction_expire_interval_daa_score
        stale = [t for t, e in self.pool.items() if e.added_daa_score < horizon]
        for t in stale:
            self._remove(t)
        return stale

    # --- selection (frontier.rs, selectors.rs) ---

    def select_transactions(
        self, max_count: int = 300, mass_limits=None, lane_limits=None
    ) -> list[MempoolTx]:
        """Frontier-driven template selection: weight-sampled under
        congestion, exact greedy otherwise (frontier.select), then a
        sequence pack bounded by the per-dimension block mass limits
        (selectors.rs SequenceSelector) and by the KIP-21 lane limits
        (selectors.rs LaneSelectionState.try_select).  Only frontier
        (ready) txs are candidates, so no in-block chaining can occur."""
        max_block_mass = mass_limits.compute if mass_limits is not None else 500_000
        lanes = (
            LaneSelectionState(lane_limits.lanes_per_block, lane_limits.gas_per_lane)
            if lane_limits is not None
            else None
        )
        chosen: list[MempoolTx] = []
        compute = transient = storage = 0
        lpb = lanes.lanes_per_block if lanes is not None else None
        for key in self.frontier.select(self._rng, max_block_mass, lanes_per_block=lpb):
            if len(chosen) >= max_count:
                break
            entry = self.pool.get(key.txid)
            if entry is None:
                continue
            if mass_limits is not None and not (
                compute + entry.mass <= mass_limits.compute
                and transient + entry.transient_mass <= mass_limits.transient
                and storage + entry.storage_mass <= mass_limits.storage
            ):
                continue  # would overflow a block mass dimension
            if lanes is not None and not lanes.try_select(key.lane, key.gas):
                continue  # would overflow the lane count or per-lane gas cap
            compute += entry.mass
            transient += entry.transient_mass
            storage += entry.storage_mass
            chosen.append(entry)
        return chosen

    def build_feerate_estimator(self, args: FeerateEstimatorArgs) -> FeerateEstimator:
        """Fee estimator over the current frontier (get_fee_estimate RPC)."""
        return self.frontier.build_feerate_estimator(args)

    # --- orphans (orphan_pool.rs) ---

    def unorphan_candidates(self, created_txids: set[bytes]) -> list[MempoolTx]:
        """Orphans whose missing parents may now exist; caller revalidates."""
        out = []
        for txid in list(self.orphans):
            entry = self.orphans[txid]
            if any(inp.previous_outpoint.transaction_id in created_txids for inp in entry.tx.inputs):
                del self.orphans[txid]
                out.append(entry)
        return out
