from kaspa_tpu.mempool.mempool import Mempool, MempoolError, MempoolTx  # noqa: F401
from kaspa_tpu.mempool.mining_manager import MiningManager  # noqa: F401
