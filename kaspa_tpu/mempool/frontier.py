"""Mempool frontier: weight-augmented search tree + feerate-weighted sampling.

The frontier is the set of pool transactions with no in-pool ancestors —
the candidates for the next block template.  Port of the reference design
(mining/src/mempool/model/frontier.rs, frontier/search_tree.rs,
frontier/selectors.rs) with the search tree realised as a weight-augmented
treap (the reference uses an augmented B+-tree; a treap gives the same
O(log n) insert/remove/weighted-search/prefix-weight surface in a fraction
of the code and is cache-friendly enough at python speed).

Selection:
- large frontiers (total mass > 4x block mass): weighted in-place sampling,
  P(tx) ∝ (fee/mass)^ALPHA, with collision narrowing via prefix weights —
  a template is a random sample skewed to high feerate, which spreads
  inclusion fairly across equal-feerate txs under congestion;
- small frontiers: exact greedy descending-feerate pack (the sampling
  distribution's limit case; the reference's take-all/mutating-tree
  selectors reduce to this outcome).

KIP-21 subnetwork lanes are intentionally absent: the framework currently
runs the pre-Toccata consensus ruleset (see ROADMAP), where every tx rides
the native lane.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

from kaspa_tpu.mempool.feerate import ALPHA, FeerateEstimator, FeerateEstimatorArgs

COLLISION_FACTOR = 4
MASS_LIMIT_FACTOR = 1.2
TARGET_GAP_FACTOR = 0.05
MAX_NULL_ATTEMPTS = 8
INITIAL_AVG_MASS = 2036.0
AVG_MASS_DECAY_FACTOR = 0.99999


@dataclass(frozen=True)
class FeerateKey:
    """Sort key: feerate asc, txid tiebreak; weight = feerate**ALPHA."""

    fee: int
    mass: int
    txid: bytes

    @property
    def feerate(self) -> float:
        return self.fee / self.mass

    @property
    def weight(self) -> float:
        return self.feerate**ALPHA

    def sort_key(self) -> tuple:
        return (self.feerate, self.txid)


class _Node:
    __slots__ = ("key", "prio", "left", "right", "subtree_weight", "subtree_count")

    def __init__(self, key: FeerateKey, prio: float):
        self.key = key
        self.prio = prio
        self.left: _Node | None = None
        self.right: _Node | None = None
        self.subtree_weight = key.weight
        self.subtree_count = 1


def _weight(n: _Node | None) -> float:
    return n.subtree_weight if n else 0.0


def _count(n: _Node | None) -> int:
    return n.subtree_count if n else 0


def _update(n: _Node) -> _Node:
    n.subtree_weight = n.key.weight + _weight(n.left) + _weight(n.right)
    n.subtree_count = 1 + _count(n.left) + _count(n.right)
    return n


class SearchTree:
    """Weight-augmented treap over FeerateKeys (frontier/search_tree.rs)."""

    def __init__(self, seed: int = 0xF0E7):
        self._root: _Node | None = None
        self._rng = random.Random(seed)
        self._ids: set[bytes] = set()

    def __len__(self) -> int:
        return _count(self._root)

    def __contains__(self, key: FeerateKey) -> bool:
        return key.txid in self._ids

    def total_weight(self) -> float:
        return _weight(self._root)

    # --- treap mechanics -------------------------------------------------

    def _split(self, node: _Node | None, sk: tuple):
        """(nodes with sort_key < sk, nodes with sort_key >= sk)."""
        if node is None:
            return None, None
        if node.key.sort_key() < sk:
            l, r = self._split(node.right, sk)
            node.right = l
            return _update(node), r
        l, r = self._split(node.left, sk)
        node.left = r
        return l, _update(node)

    def _merge(self, a: _Node | None, b: _Node | None) -> _Node | None:
        if a is None:
            return b
        if b is None:
            return a
        if a.prio >= b.prio:
            a.right = self._merge(a.right, b)
            return _update(a)
        b.left = self._merge(a, b.left)
        return _update(b)

    def insert(self, key: FeerateKey) -> bool:
        if key.txid in self._ids:
            return False
        self._ids.add(key.txid)
        node = _Node(key, self._rng.random())
        l, r = self._split(self._root, key.sort_key())
        self._root = self._merge(self._merge(l, node), r)
        return True

    def remove(self, key: FeerateKey) -> bool:
        if key.txid not in self._ids:
            return False
        self._ids.discard(key.txid)
        sk = key.sort_key()
        l, rest = self._split(self._root, sk)
        # rest's leftmost node is the key (sort keys are unique via txid)
        mid, r = self._split(rest, (sk[0], sk[1] + b"\x00"))
        assert mid is not None and mid.subtree_count == 1 and mid.key.txid == key.txid
        self._root = self._merge(l, r)
        return True

    # --- queries ---------------------------------------------------------

    def search(self, query: float) -> FeerateKey:
        """Weighted search: the key at cumulative (ascending) weight `query`."""
        node = self._root
        assert node is not None
        while True:
            lw = _weight(node.left)
            if query < lw and node.left is not None:
                node = node.left
            elif query < lw + node.key.weight or node.right is None:
                return node.key
            else:
                query -= lw + node.key.weight
                node = node.right

    def prefix_weight(self, key: FeerateKey) -> float:
        """Σ weight of keys with sort_key <= key's (log-depth exact walk)."""
        sk = key.sort_key()
        acc = 0.0
        node = self._root
        while node is not None:
            if node.key.sort_key() <= sk:
                acc += node.key.weight + _weight(node.left)
                node = node.right
            else:
                node = node.left
        return acc

    def ascending(self):
        stack, node = [], self._root
        while stack or node:
            while node:
                stack.append(node)
                node = node.left
            node = stack.pop()
            yield node.key
            node = node.right

    def descending(self):
        stack, node = [], self._root
        while stack or node:
            while node:
                stack.append(node)
                node = node.right
            node = stack.pop()
            yield node.key
            node = node.left


class _SampleMassTracker:
    """Stop condition for in-place sampling (frontier.rs SampleMassTracker)."""

    def __init__(self, max_block_mass: int):
        self.sampled = 0
        self.gap = max_block_mass
        self.desired = int(max_block_mass * MASS_LIMIT_FACTOR)
        self.null_attempts = 0
        self.target_gap = int(max_block_mass * TARGET_GAP_FACTOR)

    def should_continue(self) -> bool:
        return self.sampled <= self.desired or (
            self.null_attempts < MAX_NULL_ATTEMPTS and self.gap > self.target_gap
        )

    def record(self, mass: int) -> None:
        self.sampled += mass
        if mass <= self.gap:
            self.gap -= mass
        else:
            self.null_attempts += 1


class Frontier:
    """Ready-transaction frontier with weighted sampling + fee estimation."""

    def __init__(self, target_time_per_block_seconds: float = 1.0):
        self.tree = SearchTree()
        self.total_mass = 0
        self.average_transaction_mass = INITIAL_AVG_MASS
        self.target_time_per_block_seconds = target_time_per_block_seconds

    def __len__(self) -> int:
        return len(self.tree)

    def insert(self, key: FeerateKey) -> bool:
        if self.tree.insert(key):
            self.total_mass += key.mass
            # decaying average: recent txs weigh more, history never vanishes
            self.average_transaction_mass = (
                self.average_transaction_mass * AVG_MASS_DECAY_FACTOR
                + key.mass * (1.0 - AVG_MASS_DECAY_FACTOR)
            )
            return True
        return False

    def remove(self, key: FeerateKey) -> bool:
        if self.tree.remove(key):
            self.total_mass -= key.mass
            return True
        return False

    # --- selection -------------------------------------------------------

    def sample_inplace(self, rng: random.Random, max_block_mass: int) -> list[FeerateKey]:
        """Weighted sample of ~1.2x block mass, P(tx) ∝ weight.

        Collision narrowing: once the current top item has been sampled,
        the sampling space shrinks below it via a prefix-weight bound, so
        heavily biased weight distributions still converge in O(k log n).
        """
        assert len(self.tree) > 0
        down = self.tree.descending()
        top = next(down)
        cache: set[bytes] = set()
        sequence: list[FeerateKey] = []
        tracker = _SampleMassTracker(max_block_mass)
        space = self.tree.total_weight()
        while len(cache) < len(self.tree) and tracker.should_continue():
            query = rng.random() * space
            item = self.tree.search(query)
            exhausted = False
            while item.txid in cache:
                # narrow the space past any fully-sampled top run
                if top.txid in cache:
                    nxt = next(down, None)
                    if nxt is None:
                        exhausted = True
                        break
                    top = nxt
                    space = self.tree.prefix_weight(top)
                query = rng.random() * space
                item = self.tree.search(query)
            if exhausted:
                break
            cache.add(item.txid)
            tracker.record(item.mass)
            sequence.append(item)
        return sequence

    def select(self, rng: random.Random, max_block_mass: int) -> list[FeerateKey]:
        """Selection order for template building (build_selector)."""
        if len(self.tree) == 0:
            return []
        if self.total_mass > COLLISION_FACTOR * max_block_mass:
            return self.sample_inplace(rng, max_block_mass)
        return list(self.tree.descending())

    # --- fee estimation --------------------------------------------------

    def build_feerate_estimator(self, args: FeerateEstimatorArgs) -> FeerateEstimator:
        """Best estimator over outlier-removal prefixes (frontier.rs:389)."""
        avg_mass = self.average_transaction_mass
        bps = float(args.network_blocks_per_second)
        mass_per_block = float(args.maximum_mass_per_block)
        inclusion_interval = avg_mass / (mass_per_block * bps)
        estimator = FeerateEstimator(
            self.tree.total_weight(), inclusion_interval, self.target_time_per_block_seconds
        )
        down = self.tree.descending()
        current = next(down, None)
        while current is not None:
            # removing a top outlier consumes a block slot of its actual mass
            mass_per_block -= current.mass
            if mass_per_block <= avg_mass:
                break
            inclusion_interval = avg_mass / (mass_per_block * bps)
            nxt = next(down, None)
            prefix = self.tree.prefix_weight(nxt) if nxt is not None else 0.0
            pending = FeerateEstimator(
                prefix, inclusion_interval, self.target_time_per_block_seconds
            )
            if pending.feerate_to_time(1.0) < estimator.feerate_to_time(1.0):
                estimator = pending
            else:
                break
            current = nxt
        return estimator
