"""Mempool frontier: weight-augmented search tree + feerate-weighted sampling.

The frontier is the set of pool transactions with no in-pool ancestors —
the candidates for the next block template.  Port of the reference design
(mining/src/mempool/model/frontier.rs, frontier/search_tree.rs,
frontier/selectors.rs) with the search tree realised as a weight-augmented
treap (the reference uses an augmented B+-tree; a treap gives the same
O(log n) insert/remove/weighted-search/prefix-weight surface in a fraction
of the code and is cache-friendly enough at python speed).

Selection:
- large frontiers (total mass > 4x block mass): weighted in-place sampling,
  P(tx) ∝ (fee/mass)^ALPHA, with collision narrowing via prefix weights —
  a template is a random sample skewed to high feerate, which spreads
  inclusion fairly across equal-feerate txs under congestion;
- small frontiers: exact greedy descending-feerate pack (the sampling
  distribution's limit case; the reference's take-all/mutating-tree
  selectors reduce to this outcome).

KIP-21 subnetwork lanes (frontier.rs:166-185): frontier keys carry their
lane (subnetwork id) and gas, and sampling freezes the lane set once it
would spill past the lanes-per-block limit — the remainder of the sample is
a best-feerate-first fill within the already-occupied lanes only (the
reference k-way-merges per-lane B-trees; a filtered walk of the global tree
yields the identical order).  Selection-time gas/lane caps are enforced by
LaneSelectionState (selectors.rs:28-66), matching the consensus
body-in-isolation lane rules so templates are never built invalid.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field

from kaspa_tpu.consensus.model import SUBNETWORK_ID_NATIVE
from kaspa_tpu.mempool.feerate import ALPHA, FeerateEstimator, FeerateEstimatorArgs

COLLISION_FACTOR = 4
MASS_LIMIT_FACTOR = 1.2
TARGET_GAP_FACTOR = 0.05
MAX_NULL_ATTEMPTS = 8
INITIAL_AVG_MASS = 2036.0
AVG_MASS_DECAY_FACTOR = 0.99999


@dataclass(frozen=True)
class FeerateKey:
    """Sort key: feerate asc, txid tiebreak; weight = feerate**ALPHA.

    Carries the tx's KIP-21 lane (subnetwork id) and gas so selection can
    enforce the block lane limits (frontier/feerate_key.rs `lane()`)."""

    fee: int
    mass: int
    txid: bytes
    lane: bytes = SUBNETWORK_ID_NATIVE
    gas: int = 0

    @property
    def feerate(self) -> float:
        return self.fee / self.mass

    @property
    def weight(self) -> float:
        return self.feerate**ALPHA

    def sort_key(self) -> tuple:
        return (self.feerate, self.txid)


class _Node:
    __slots__ = ("key", "prio", "left", "right", "subtree_weight", "subtree_count")

    def __init__(self, key: FeerateKey, prio: float):
        self.key = key
        self.prio = prio
        self.left: _Node | None = None
        self.right: _Node | None = None
        self.subtree_weight = key.weight
        self.subtree_count = 1


def _weight(n: _Node | None) -> float:
    return n.subtree_weight if n else 0.0


def _count(n: _Node | None) -> int:
    return n.subtree_count if n else 0


def _update(n: _Node) -> _Node:
    n.subtree_weight = n.key.weight + _weight(n.left) + _weight(n.right)
    n.subtree_count = 1 + _count(n.left) + _count(n.right)
    return n


class SearchTree:
    """Weight-augmented treap over FeerateKeys (frontier/search_tree.rs)."""

    def __init__(self, seed: int = 0xF0E7):
        self._root: _Node | None = None
        self._rng = random.Random(seed)
        self._ids: set[bytes] = set()

    def __len__(self) -> int:
        return _count(self._root)

    def __contains__(self, key: FeerateKey) -> bool:
        return key.txid in self._ids

    def total_weight(self) -> float:
        return _weight(self._root)

    # --- treap mechanics -------------------------------------------------

    def _split(self, node: _Node | None, sk: tuple):
        """(nodes with sort_key < sk, nodes with sort_key >= sk)."""
        if node is None:
            return None, None
        if node.key.sort_key() < sk:
            l, r = self._split(node.right, sk)
            node.right = l
            return _update(node), r
        l, r = self._split(node.left, sk)
        node.left = r
        return l, _update(node)

    def _merge(self, a: _Node | None, b: _Node | None) -> _Node | None:
        if a is None:
            return b
        if b is None:
            return a
        if a.prio >= b.prio:
            a.right = self._merge(a.right, b)
            return _update(a)
        b.left = self._merge(a, b.left)
        return _update(b)

    def insert(self, key: FeerateKey) -> bool:
        if key.txid in self._ids:
            return False
        self._ids.add(key.txid)
        node = _Node(key, self._rng.random())
        l, r = self._split(self._root, key.sort_key())
        self._root = self._merge(self._merge(l, node), r)
        return True

    def remove(self, key: FeerateKey) -> bool:
        if key.txid not in self._ids:
            return False
        self._ids.discard(key.txid)
        sk = key.sort_key()
        l, rest = self._split(self._root, sk)
        # rest's leftmost node is the key (sort keys are unique via txid)
        mid, r = self._split(rest, (sk[0], sk[1] + b"\x00"))
        assert mid is not None and mid.subtree_count == 1 and mid.key.txid == key.txid
        self._root = self._merge(l, r)
        return True

    # --- queries ---------------------------------------------------------

    def search(self, query: float) -> FeerateKey:
        """Weighted search: the key at cumulative (ascending) weight `query`."""
        node = self._root
        assert node is not None
        while True:
            lw = _weight(node.left)
            if query < lw and node.left is not None:
                node = node.left
            elif query < lw + node.key.weight or node.right is None:
                return node.key
            else:
                query -= lw + node.key.weight
                node = node.right

    def prefix_weight(self, key: FeerateKey) -> float:
        """Σ weight of keys with sort_key <= key's (log-depth exact walk)."""
        sk = key.sort_key()
        acc = 0.0
        node = self._root
        while node is not None:
            if node.key.sort_key() <= sk:
                acc += node.key.weight + _weight(node.left)
                node = node.right
            else:
                node = node.left
        return acc

    def ascending(self):
        stack, node = [], self._root
        while stack or node:
            while node:
                stack.append(node)
                node = node.left
            node = stack.pop()
            yield node.key
            node = node.right

    def descending(self):
        stack, node = [], self._root
        while stack or node:
            while node:
                stack.append(node)
                node = node.right
            node = stack.pop()
            yield node.key
            node = node.left


@dataclass
class _LaneUsage:
    tx_count: int = 0
    gas: int = 0


@dataclass
class LaneSelectionState:
    """Selection-time KIP-21 lane gating (selectors.rs LaneSelectionState).

    LPB and gas are enforced during selection, but gas is intentionally not
    part of the global feerate weight since gas capacity is lane-local.
    The reference additionally carries a `reject` rollback for txs the
    template builder later drops; here selection is final — frontier txs are
    pre-validated at mempool intake against the virtual view — so no
    rollback path exists."""

    lanes_per_block: int
    gas_per_lane: int
    occupied: dict[bytes, _LaneUsage] = field(default_factory=dict)

    def try_select(self, lane: bytes, gas: int) -> bool:
        usage = self.occupied.get(lane)
        if usage is not None:
            if usage.gas + gas > self.gas_per_lane:
                return False
            usage.tx_count += 1
            usage.gas += gas
            return True
        if len(self.occupied) >= self.lanes_per_block or gas > self.gas_per_lane:
            return False
        self.occupied[lane] = _LaneUsage(1, gas)
        return True


class _SampleMassTracker:
    """Stop condition for in-place sampling (frontier.rs SampleMassTracker)."""

    def __init__(self, max_block_mass: int):
        self.sampled = 0
        self.gap = max_block_mass
        self.desired = int(max_block_mass * MASS_LIMIT_FACTOR)
        self.null_attempts = 0
        self.target_gap = int(max_block_mass * TARGET_GAP_FACTOR)

    def should_continue(self) -> bool:
        return self.sampled <= self.desired or (
            self.null_attempts < MAX_NULL_ATTEMPTS and self.gap > self.target_gap
        )

    def record(self, mass: int) -> None:
        self.sampled += mass
        if mass <= self.gap:
            self.gap -= mass
        else:
            self.null_attempts += 1


class Frontier:
    """Ready-transaction frontier with weighted sampling + fee estimation."""

    def __init__(self, target_time_per_block_seconds: float = 1.0):
        self.tree = SearchTree()
        # lane -> live key count: bounds the lane-frozen fill walk without
        # maintaining per-lane ordered structures (frontier.rs keeps per-lane
        # B-trees; a count is enough for the filtered-walk realization)
        self.lane_counts: dict[bytes, int] = {}
        self.total_mass = 0
        self.average_transaction_mass = INITIAL_AVG_MASS
        self.target_time_per_block_seconds = target_time_per_block_seconds

    def __len__(self) -> int:
        return len(self.tree)

    def insert(self, key: FeerateKey) -> bool:
        if self.tree.insert(key):
            self.lane_counts[key.lane] = self.lane_counts.get(key.lane, 0) + 1
            self.total_mass += key.mass
            # decaying average: recent txs weigh more, history never vanishes
            self.average_transaction_mass = (
                self.average_transaction_mass * AVG_MASS_DECAY_FACTOR
                + key.mass * (1.0 - AVG_MASS_DECAY_FACTOR)
            )
            return True
        return False

    def remove(self, key: FeerateKey) -> bool:
        if self.tree.remove(key):
            n = self.lane_counts.get(key.lane, 0) - 1
            if n > 0:
                self.lane_counts[key.lane] = n
            else:
                self.lane_counts.pop(key.lane, None)
            self.total_mass -= key.mass
            return True
        return False

    # --- selection -------------------------------------------------------

    def sample_inplace(
        self, rng: random.Random, max_block_mass: int, lanes_per_block: int | None = None
    ) -> list[FeerateKey]:
        """Weighted sample of ~1.2x block mass, P(tx) ∝ weight.

        Collision narrowing: once the current top item has been sampled,
        the sampling space shrinks below it via a prefix-weight bound, so
        heavily biased weight distributions still converge in O(k log n).

        Lane freeze (frontier.rs sample_inplace): sampling stays fully
        weighted until the sampled sequence first occupies `lanes_per_block`
        lanes; the first attempt to spill outside them freezes the lane set
        and the remainder is a best-first merge within those lanes only.
        """
        assert len(self.tree) > 0
        down = self.tree.descending()
        top = next(down)
        cache: set[bytes] = set()
        sequence: list[FeerateKey] = []
        tracker = _SampleMassTracker(max_block_mass)
        space = self.tree.total_weight()
        occupied: set[bytes] = set()
        frozen = False
        while len(cache) < len(self.tree) and tracker.should_continue():
            query = rng.random() * space
            item = self.tree.search(query)
            exhausted = False
            while item.txid in cache:
                # narrow the space past any fully-sampled top run
                if top.txid in cache:
                    nxt = next(down, None)
                    if nxt is None:
                        exhausted = True
                        break
                    top = nxt
                    space = self.tree.prefix_weight(top)
                query = rng.random() * space
                item = self.tree.search(query)
            if exhausted:
                break
            if lanes_per_block is not None:
                if len(occupied) < lanes_per_block:
                    occupied.add(item.lane)
                elif item.lane not in occupied:
                    # the weighted sampler wants to spill outside the first
                    # LPB discovered lanes: freeze and fill intra-lane
                    frozen = True
                    break
            cache.add(item.txid)
            tracker.record(item.mass)
            sequence.append(item)
        if frozen:
            self._finish_intra_lane_selection(sequence, cache, occupied, tracker)
        return sequence

    def _finish_intra_lane_selection(
        self,
        sequence: list[FeerateKey],
        cache: set[bytes],
        occupied: set[bytes],
        tracker: _SampleMassTracker,
    ) -> None:
        """Complete a lane-frozen sample from the occupied lanes only,
        best-feerate-first (frontier.rs finish_intra_lane_selection).  The
        reference k-way-merges per-lane B-tree heads; a descending walk of
        the global tree filtered to the occupied lanes yields the identical
        order.  The walk stops at the mass budget or once every live
        occupied-lane entry has been seen (lane_counts bound) — it does not
        scan the tail of a large tree whose occupied-lane items are spent."""
        remaining = sum(self.lane_counts.get(lane, 0) for lane in occupied)
        for item in self.tree.descending():
            if remaining <= 0 or not tracker.should_continue():
                break
            if item.lane not in occupied:
                continue
            remaining -= 1
            if item.txid in cache:
                continue
            sequence.append(item)
            tracker.record(item.mass)

    def select(
        self, rng: random.Random, max_block_mass: int, lanes_per_block: int | None = None
    ) -> list[FeerateKey]:
        """Selection order for template building (build_selector)."""
        if len(self.tree) == 0:
            return []
        if self.total_mass > COLLISION_FACTOR * max_block_mass:
            return self.sample_inplace(rng, max_block_mass, lanes_per_block)
        return list(self.tree.descending())

    # --- fee estimation --------------------------------------------------

    def build_feerate_estimator(self, args: FeerateEstimatorArgs) -> FeerateEstimator:
        """Best estimator over outlier-removal prefixes (frontier.rs:389)."""
        avg_mass = self.average_transaction_mass
        bps = float(args.network_blocks_per_second)
        mass_per_block = float(args.maximum_mass_per_block)
        inclusion_interval = avg_mass / (mass_per_block * bps)
        estimator = FeerateEstimator(
            self.tree.total_weight(), inclusion_interval, self.target_time_per_block_seconds
        )
        down = self.tree.descending()
        current = next(down, None)
        while current is not None:
            # removing a top outlier consumes a block slot of its actual mass
            mass_per_block -= current.mass
            if mass_per_block <= avg_mass:
                break
            inclusion_interval = avg_mass / (mass_per_block * bps)
            nxt = next(down, None)
            prefix = self.tree.prefix_weight(nxt) if nxt is not None else 0.0
            pending = FeerateEstimator(
                prefix, inclusion_interval, self.target_time_per_block_seconds
            )
            if pending.feerate_to_time(1.0) < estimator.feerate_to_time(1.0):
                estimator = pending
            else:
                break
            current = nxt
        return estimator
