from kaspa_tpu.notify.notifier import (  # noqa: F401
    EVENT_TYPES,
    Notification,
    Notifier,
    Subscription,
)
