"""Notification pub/sub pipeline.

Reference: notify/src/ (Notifier with per-listener subscriptions,
Broadcaster, Collector/Subscriber chaining; events.rs EventType).  The
chain consensus-root -> NotifyService -> IndexService -> RpcCoreService is
modeled as Notifier stages that can be linked parent->child, with
UtxosChanged address filtering per listener
(notify/src/address/ + subscription/).

Synchronous in-process delivery in this round; the async broadcaster tasks
arrive with the service-runtime milestone.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable

from kaspa_tpu.observability import trace

# notify/src/events.rs:44-55 (9 event types)
EVENT_TYPES = (
    "block-added",
    "virtual-chain-changed",
    "finality-conflict",
    "finality-conflict-resolved",
    "utxos-changed",
    "sink-blue-score-changed",
    "virtual-daa-score-changed",
    "pruning-point-utxo-set-override",
    "new-block-template",
)


@dataclass
class Notification:
    event_type: str
    data: dict
    # producer-side TraceContext, captured at construction: the serving
    # broadcaster/sender threads re-attach fanout + delivery spans to the
    # block trace that emitted the event
    ctx: object = None
    # origin-block accept stamp (perf_counter_ns at construction on the
    # consensus thread): the serving tier measures block-accept -> wire
    # lag against this, and conflation keeps the OLDEST stamp so merged
    # diffs cannot hide staleness.  Carried outside ``data`` so payload
    # bytes are identical with or without latency tracing.
    t_accept_ns: int = 0
    # how many earlier diffs were conflated into this one (0 = pristine)
    merged: int = 0

    def __post_init__(self):
        if self.ctx is None:
            self.ctx = trace.context()
        if self.t_accept_ns == 0:
            self.t_accept_ns = time.perf_counter_ns()


@dataclass
class Subscription:
    """Per-listener, per-event subscription state.

    For utxos-changed: `addresses` empty == all addresses (wildcard),
    else filter to the tracked set (notify/src/subscription/single.rs).
    """

    event_type: str
    active: bool = False
    addresses: set[bytes] = field(default_factory=set)  # script pubkey filter

    def matches(self, notification: Notification) -> bool:
        if not self.active or notification.event_type != self.event_type:
            return False
        if self.event_type == "utxos-changed" and self.addresses:
            changed = notification.data.get("spk_set", set())
            return bool(changed & self.addresses)
        return True

    def filter(self, notification: Notification) -> Notification:
        if self.event_type != "utxos-changed" or not self.addresses:
            return notification
        data = dict(notification.data)
        data["added"] = [u for u in data.get("added", []) if u[1].script_public_key.script in self.addresses]
        data["removed"] = [u for u in data.get("removed", []) if u[1].script_public_key.script in self.addresses]
        return Notification(
            notification.event_type, data, notification.ctx,
            t_accept_ns=notification.t_accept_ns, merged=notification.merged,
        )


class Listener:
    def __init__(self, listener_id: int, callback: Callable[[Notification], None]):
        self.id = listener_id
        self.callback = callback
        self.subscriptions: dict[str, Subscription] = {e: Subscription(e) for e in EVENT_TYPES}


class Notifier:
    """notify/src/notifier.rs: listener registry + dispatch + upstream link."""

    def __init__(self, name: str = "notifier", parent: "Notifier | None" = None):
        self.name = name
        self._listeners: dict[int, Listener] = {}
        self._next_id = 1
        self.parent = parent
        self._parent_listener_id = None
        if parent is not None:
            # Subscriber: propagate notifications (and subscriptions) upstream
            self._parent_listener_id = parent.register(self.notify)

    def rebind_parent(self, new_parent: "Notifier") -> None:
        """Re-chain this notifier onto a new upstream (consensus staging
        swap): listeners and their subscriptions survive; active event
        types are re-propagated so the new root keeps publishing them."""
        if self.parent is not None and self._parent_listener_id is not None:
            self.parent.unregister(self._parent_listener_id)
        self.parent = new_parent
        self._parent_listener_id = new_parent.register(self.notify)
        for event in EVENT_TYPES:
            subs = [l.subscriptions[event] for l in self._listeners.values()]
            if any(s.active for s in subs):
                addresses = set().union(*(s.addresses for s in subs if s.active)) or None
                new_parent.start_notify(self._parent_listener_id, event, addresses)

    def register(self, callback: Callable[[Notification], None]) -> int:
        lid = self._next_id
        self._next_id += 1
        self._listeners[lid] = Listener(lid, callback)
        return lid

    def unregister(self, listener_id: int) -> None:
        self._listeners.pop(listener_id, None)

    def start_notify(self, listener_id: int, event_type: str, addresses: set[bytes] | None = None) -> None:
        sub = self._listeners[listener_id].subscriptions[event_type]
        sub.active = True
        if addresses is not None:
            sub.addresses |= addresses
        if self.parent is not None:
            self.parent.start_notify(self._parent_listener_id, event_type, addresses)

    def stop_notify(self, listener_id: int, event_type: str, addresses: set[bytes] | None = None) -> None:
        sub = self._listeners[listener_id].subscriptions[event_type]
        if addresses:
            if not sub.addresses:
                return  # wildcard subscription: removing specific addresses is a no-op
            sub.addresses -= addresses
            if sub.addresses:
                return
        sub.active = False
        sub.addresses.clear()
        # propagate the stop upstream only once no local listener needs the event
        if self.parent is not None and not any(
            l.subscriptions[event_type].active for l in self._listeners.values()
        ):
            self.parent.stop_notify(self._parent_listener_id, event_type)

    def has_subscribers(self, event_type: str) -> bool:
        """True when any listener (directly or via a chained child
        notifier) holds an active subscription for the event."""
        return any(
            l.subscriptions[event_type].active for l in self._listeners.values()
        )

    def notify(self, notification: Notification) -> None:
        """Broadcast to all matching listeners (Broadcaster role)."""
        for listener in list(self._listeners.values()):
            sub = listener.subscriptions.get(notification.event_type)
            if sub is not None and sub.matches(notification):
                listener.callback(sub.filter(notification))


class ConsensusNotificationRoot(Notifier):
    """consensus/notify/src/root.rs: the source of consensus events."""

    def __init__(self):
        super().__init__("consensus-root")

    def notify_block_added(self, block, ctx=None):
        # ctx: the block's own TraceContext — the pipeline's virtual worker
        # passes it per task so fanout spans land in the right block trace
        # even when one virtual cycle absorbs a whole batch
        self.notify(Notification("block-added", {"block": block}, ctx))

    def notify_virtual_change(self, virtual_state, added_utxos, removed_utxos):
        self.notify(
            Notification(
                "virtual-daa-score-changed",
                {"daa_score": virtual_state.daa_score},
            )
        )
        self.notify(
            Notification(
                "sink-blue-score-changed",
                {"blue_score": virtual_state.ghostdag_data.blue_score},
            )
        )
        if added_utxos or removed_utxos:
            spk_set = {e.script_public_key.script for _, e in added_utxos} | {
                e.script_public_key.script for _, e in removed_utxos
            }
            self.notify(
                Notification(
                    "utxos-changed",
                    {
                        "added": added_utxos,
                        "removed": removed_utxos,
                        "spk_set": spk_set,
                        # carried so remote consumers can classify coinbase
                        # maturity without a separate daa-score subscription
                        "virtual_daa_score": virtual_state.daa_score,
                        # the materialized selected-chain position this diff
                        # moves a consumer to — the persistent utxoindex
                        # journals (prev, sink) per applied diff so a crash
                        # between index commit and consensus flush can be
                        # rewound instead of triggering a full resync
                        "sink": virtual_state.ghostdag_data.selected_parent,
                    },
                )
            )
