"""Vendored message-schema table (protocol/p2p/proto/{p2p,messages}.proto).

Each descriptor mirrors the reference proto definition: same field numbers,
same scalar kinds, same nesting — so bytes we emit parse in a prost/tonic
stack and vice versa.  A descriptor is::

    {"name": str, "fields": {field_number: (name, kind, repeated, nested)}}

with kinds from wire_format (uint32/uint64/int64/sint64/bool/bytes/string/
message).

Two deliberate deviations, both riding protobuf's unknown-field rule so a
reference decoder simply skips them:

- **Extension fields** numbered >= 1000 inside reference messages carry
  payload our flows need but the reference schema lacks (chunk offsets and
  done flags where the reference streams separate control messages;
  ComputeCommit budgets and covenants from the local consensus extensions).
- **Extension payloads** numbered >= 1000 in the ``KaspadMessage`` oneof
  carry whole message types with no reference analog (KIP-21 SMT state,
  the chunked IBD block stream, trusted-data blobs).

Everything in the reference-numbered range is structurally faithful.
"""

from __future__ import annotations


def _msg(name: str, *fields) -> dict:
    return {
        "name": name,
        "fields": {num: (fname, kind, repeated, nested) for num, fname, kind, repeated, nested in fields},
    }


def _f(num: int, name: str, kind: str, repeated: bool = False, message: dict | None = None):
    return (num, name, kind, repeated, message)


# -- shared leaf messages (p2p.proto) --------------------------------------

HASH = _msg("Hash", _f(1, "bytes", "bytes"))
TRANSACTION_ID = _msg("TransactionId", _f(1, "bytes", "bytes"))
SUBNETWORK_ID = _msg("SubnetworkId", _f(1, "bytes", "bytes"))

NET_ADDRESS = _msg(
    "NetAddress",
    _f(1, "timestamp", "int64"),
    _f(3, "ip", "bytes"),
    _f(4, "port", "uint32"),
)

OUTPOINT = _msg(
    "Outpoint",
    _f(1, "transactionId", "message", message=TRANSACTION_ID),
    _f(2, "index", "uint32"),
)

SCRIPT_PUBLIC_KEY = _msg(
    "ScriptPublicKey",
    _f(1, "script", "bytes"),
    _f(2, "version", "uint32"),
)

# covenantId is a local consensus extension (tx.rs Covenant) — ext field
UTXO_ENTRY = _msg(
    "UtxoEntry",
    _f(1, "amount", "uint64"),
    _f(2, "scriptPublicKey", "message", message=SCRIPT_PUBLIC_KEY),
    _f(3, "blockDaaScore", "uint64"),
    _f(4, "isCoinbase", "bool"),
    _f(1000, "covenantId", "bytes"),
)

OUTPOINT_AND_UTXO_ENTRY_PAIR = _msg(
    "OutpointAndUtxoEntryPair",
    _f(1, "outpoint", "message", message=OUTPOINT),
    _f(2, "utxoEntry", "message", message=UTXO_ENTRY),
)

COVENANT = _msg(
    "Covenant",
    _f(1, "authorizingInput", "uint32"),
    _f(2, "covenantId", "bytes"),
)

TRANSACTION_INPUT = _msg(
    "TransactionInput",
    _f(1, "previousOutpoint", "message", message=OUTPOINT),
    _f(2, "signatureScript", "bytes"),
    _f(3, "sequence", "uint64"),
    _f(4, "sigOpCount", "uint32"),
    # v1+ txs carry a compute budget instead of a sig-op count
    # (ComputeCommit, tx.rs:71-97) — extension field
    _f(1000, "computeBudget", "uint32"),
)

TRANSACTION_OUTPUT = _msg(
    "TransactionOutput",
    _f(1, "value", "uint64"),
    _f(2, "scriptPublicKey", "message", message=SCRIPT_PUBLIC_KEY),
    _f(1000, "covenant", "message", message=COVENANT),
)

TRANSACTION = _msg(
    "TransactionMessage",
    _f(1, "version", "uint32"),
    _f(2, "inputs", "message", repeated=True, message=TRANSACTION_INPUT),
    _f(3, "outputs", "message", repeated=True, message=TRANSACTION_OUTPUT),
    _f(4, "lockTime", "uint64"),
    _f(5, "subnetworkId", "message", message=SUBNETWORK_ID),
    _f(6, "gas", "uint64"),
    _f(8, "payload", "bytes"),
    _f(9, "mass", "uint64"),  # KIP-9 committed storage mass
)

BLOCK_LEVEL_PARENTS = _msg(
    "BlockLevelParents",
    _f(1, "parentHashes", "message", repeated=True, message=HASH),
)

BLOCK_HEADER = _msg(
    "BlockHeader",
    _f(1, "version", "uint32"),
    _f(3, "hashMerkleRoot", "message", message=HASH),
    _f(4, "acceptedIdMerkleRoot", "message", message=HASH),
    _f(5, "utxoCommitment", "message", message=HASH),
    _f(6, "timestamp", "int64"),
    _f(7, "bits", "uint32"),
    _f(8, "nonce", "uint64"),
    _f(9, "daaScore", "uint64"),
    _f(10, "blueWork", "bytes"),  # minimal big-endian Uint192
    _f(12, "parents", "message", repeated=True, message=BLOCK_LEVEL_PARENTS),
    _f(13, "blueScore", "uint64"),
    _f(14, "pruningPoint", "message", message=HASH),
)

BLOCK = _msg(
    "BlockMessage",
    _f(1, "header", "message", message=BLOCK_HEADER),
    _f(2, "transactions", "message", repeated=True, message=TRANSACTION),
)

# -- handshake / control ---------------------------------------------------

VERSION = _msg(
    "VersionMessage",
    _f(1, "protocolVersion", "uint32"),
    _f(2, "services", "uint64"),
    _f(3, "timestamp", "int64"),
    _f(4, "address", "message", message=NET_ADDRESS),
    _f(5, "id", "bytes"),
    _f(6, "userAgent", "string"),
    _f(8, "disableRelayTx", "bool"),
    _f(9, "subnetworkId", "message", message=SUBNETWORK_ID),
    _f(10, "network", "string"),
)

VERACK = _msg("VerackMessage")
PING = _msg("PingMessage", _f(1, "nonce", "uint64"))
PONG = _msg("PongMessage", _f(1, "nonce", "uint64"))
REJECT = _msg("RejectMessage", _f(1, "reason", "string"))

REQUEST_ADDRESSES = _msg(
    "RequestAddressesMessage",
    _f(1, "includeAllSubnetworks", "bool"),
    _f(2, "subnetworkId", "message", message=SUBNETWORK_ID),
)
ADDRESSES = _msg(
    "AddressesMessage",
    _f(1, "addressList", "message", repeated=True, message=NET_ADDRESS),
)

# -- relay -----------------------------------------------------------------

INV_RELAY_BLOCK = _msg("InvRelayBlockMessage", _f(1, "hash", "message", message=HASH))
REQUEST_RELAY_BLOCKS = _msg(
    "RequestRelayBlocksMessage", _f(1, "hashes", "message", repeated=True, message=HASH)
)
INV_TRANSACTIONS = _msg(
    "InvTransactionsMessage", _f(1, "ids", "message", repeated=True, message=TRANSACTION_ID)
)
REQUEST_TRANSACTIONS = _msg(
    "RequestTransactionsMessage", _f(1, "ids", "message", repeated=True, message=TRANSACTION_ID)
)

# -- IBD -------------------------------------------------------------------

# reference streams headers with separate RequestNextHeaders/DoneHeaders
# control messages; our flow layer rides done/continuation on the chunk
# itself — extension fields a reference decoder skips
REQUEST_HEADERS = _msg(
    "RequestHeadersMessage",
    _f(1, "lowHash", "message", message=HASH),
    _f(2, "highHash", "message", message=HASH),
)
BLOCK_HEADERS = _msg(
    "BlockHeadersMessage",
    _f(1, "blockHeaders", "message", repeated=True, message=BLOCK_HEADER),
    _f(1000, "done", "bool"),
    _f(1001, "continuation", "bytes"),
)

REQUEST_PP_PROOF = _msg("RequestPruningPointProofMessage")
PP_PROOF_HEADER_ARRAY = _msg(
    "PruningPointProofHeaderArray",
    _f(1, "headers", "message", repeated=True, message=BLOCK_HEADER),
)
PP_PROOF = _msg(
    "PruningPointProofMessage",
    _f(1, "headers", "message", repeated=True, message=PP_PROOF_HEADER_ARRAY),
)

REQUEST_PP_UTXOS = _msg(
    "RequestPruningPointUTXOSetMessage",
    _f(1, "pruningPointHash", "message", message=HASH),
    _f(1000, "offset", "uint64"),  # our chunk paging (reference uses RequestNext)
)
PP_UTXO_CHUNK = _msg(
    "PruningPointUtxoSetChunkMessage",
    _f(1, "outpointAndUtxoEntryPairs", "message", repeated=True, message=OUTPOINT_AND_UTXO_ENTRY_PAIR),
    _f(1000, "offset", "uint64"),
    _f(1001, "done", "bool"),
)

IBD_CHAIN_BLOCK_LOCATOR = _msg(
    "IbdChainBlockLocatorMessage",
    _f(1, "blockLocatorHashes", "message", repeated=True, message=HASH),
)
REQUEST_ANTICONE = _msg(
    "RequestAnticoneMessage",
    _f(1, "blockHash", "message", message=HASH),
    _f(2, "contextHash", "message", message=HASH),
)

# -- extension payloads (no reference analog; oneof numbers >= 1000) -------

IBD_BLOCKS_CHUNK = _msg(
    "IbdBlocksChunkMessage",
    _f(1, "blocks", "message", repeated=True, message=BLOCK),
    _f(2, "done", "bool"),
    _f(3, "continuation", "bytes"),
)
REQUEST_IBD_CHAIN_INFO = _msg("RequestIbdChainInfoMessage")
IBD_CHAIN_INFO = _msg(
    "IbdChainInfoMessage",
    _f(1, "sink", "bytes"),
    _f(2, "sinkBlueWork", "bytes"),  # minimal big-endian, like blueWork
    _f(3, "pruningPoint", "bytes"),
)
REQUEST_TRUSTED_DATA = _msg("RequestTrustedDataMessage")
# the trusted-data bundle (headers + ghostdag + windows + bodies maps) and
# the KIP-21 SMT chunk keep their canonical serde layout inside a bytes
# envelope: the flows consume them whole, and re-projecting the nested
# maps into proto would buy no interop (no reference schema exists)
TRUSTED_DATA_BLOB = _msg("TrustedDataBlobMessage", _f(1, "blob", "bytes"))
REQUEST_PP_SMT = _msg(
    "RequestPruningPointSmtStateMessage",
    _f(1, "pruningPointHash", "bytes"),
    _f(2, "offset", "uint64"),
)
PP_SMT_CHUNK_BLOB = _msg("PruningPointSmtStateChunkMessage", _f(1, "blob", "bytes"))
REQUEST_BLOCK_BODIES = _msg(
    "RequestBlockBodiesMessage", _f(1, "hashes", "message", repeated=True, message=HASH)
)
BLOCK_BODY_ENTRY = _msg(
    "BlockBodyEntry",
    _f(1, "hash", "bytes"),
    _f(2, "transactions", "message", repeated=True, message=TRANSACTION),
)
BLOCK_BODIES = _msg(
    "BlockBodiesMessage",
    _f(1, "entries", "message", repeated=True, message=BLOCK_BODY_ENTRY),
)

# -- the KaspadMessage oneof (messages.proto) ------------------------------

# oneof field numbers < 1000 are the reference's messages.proto numbering;
# >= 1000 are extension payloads (skipped by a reference decoder)
KASPAD_MESSAGE = _msg(
    "KaspadMessage",
    _f(1, "addresses", "message", message=ADDRESSES),
    _f(2, "block", "message", message=BLOCK),
    _f(3, "transaction", "message", message=TRANSACTION),
    _f(6, "requestAddresses", "message", message=REQUEST_ADDRESSES),
    _f(10, "requestRelayBlocks", "message", message=REQUEST_RELAY_BLOCKS),
    _f(12, "requestTransactions", "message", message=REQUEST_TRANSACTIONS),
    _f(14, "invRelayBlock", "message", message=INV_RELAY_BLOCK),
    _f(15, "invTransactions", "message", message=INV_TRANSACTIONS),
    _f(16, "ping", "message", message=PING),
    _f(17, "pong", "message", message=PONG),
    _f(19, "verack", "message", message=VERACK),
    _f(20, "version", "message", message=VERSION),
    _f(22, "reject", "message", message=REJECT),
    _f(25, "pruningPointUtxoSetChunk", "message", message=PP_UTXO_CHUNK),
    _f(36, "requestPruningPointUTXOSet", "message", message=REQUEST_PP_UTXOS),
    _f(37, "requestHeaders", "message", message=REQUEST_HEADERS),
    _f(41, "blockHeaders", "message", message=BLOCK_HEADERS),
    _f(42, "requestPruningPointProof", "message", message=REQUEST_PP_PROOF),
    _f(43, "pruningPointProof", "message", message=PP_PROOF),
    _f(48, "ibdChainBlockLocator", "message", message=IBD_CHAIN_BLOCK_LOCATOR),
    _f(49, "requestAnticone", "message", message=REQUEST_ANTICONE),
    _f(1001, "ibdBlocksChunk", "message", message=IBD_BLOCKS_CHUNK),
    _f(1002, "requestIbdChainInfo", "message", message=REQUEST_IBD_CHAIN_INFO),
    _f(1003, "ibdChainInfo", "message", message=IBD_CHAIN_INFO),
    _f(1004, "requestTrustedData", "message", message=REQUEST_TRUSTED_DATA),
    _f(1005, "trustedData", "message", message=TRUSTED_DATA_BLOB),
    _f(1008, "requestPruningPointSmtState", "message", message=REQUEST_PP_SMT),
    _f(1009, "pruningPointSmtStateChunk", "message", message=PP_SMT_CHUNK_BLOB),
    _f(1010, "requestBlockBodies", "message", message=REQUEST_BLOCK_BODIES),
    _f(1011, "blockBodies", "message", message=BLOCK_BODIES),
)
