"""Deterministic sample payloads for every vendored KaspadMessage type.

One representative payload per flow message type, built from fixed bytes —
the input side of the golden-vector fixtures pinned under
``tests/fixtures/proto/``.  ``tools/gen_proto_fixtures.py`` encodes these
into the pinned ``.bin`` files; ``tests/test_proto_wire.py`` asserts that
today's codec still produces byte-identical encodings and round-trips them
back to equal payloads.  Change a schema field and the fixture diff shows
exactly which wire bytes moved.
"""

from __future__ import annotations

from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.consensus.model.header import Header
from kaspa_tpu.consensus.model.tx import (
    ComputeCommit,
    Covenant,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)
from kaspa_tpu.consensus.processes.pruning_proof import TrustedData
from kaspa_tpu.consensus.stores import GhostdagData
from kaspa_tpu.p2p import node as p2p_node
from kaspa_tpu.p2p.wire import MSG_PING, MSG_PONG


def _bh(i: int) -> bytes:
    """Deterministic 32-byte hash: byte `i` repeated."""
    return bytes([i]) * 32


def sample_header(seed: int = 1) -> Header:
    return Header(
        version=1,
        parents_by_level=[[_bh(seed), _bh(seed + 1)], [_bh(seed + 2)]],
        hash_merkle_root=_bh(seed + 3),
        accepted_id_merkle_root=_bh(seed + 4),
        utxo_commitment=_bh(seed + 5),
        timestamp=1_700_000_000_000 + seed,
        bits=0x1E7FFFFF,
        nonce=0xDEADBEEF + seed,
        daa_score=1000 + seed,
        blue_work=0xCAFE_F00D_0000 + seed,
        blue_score=900 + seed,
        pruning_point=_bh(seed + 6),
    )


def sample_tx(seed: int = 1, budget: bool = True) -> Transaction:
    cc = ComputeCommit.budget(5000 + seed) if budget else ComputeCommit.sigops(2)
    return Transaction(
        version=1 if budget else 0,
        inputs=[
            TransactionInput(
                TransactionOutpoint(_bh(seed + 7), 3),
                b"\x41" * 65,
                0xFFFFFFFF,
                cc,
            )
        ],
        outputs=[
            TransactionOutput(50_000_000, ScriptPublicKey(0, b"\x20" + _bh(seed + 8) + b"\xac"), None),
            TransactionOutput(
                7_000_000,
                ScriptPublicKey(0, b"\x51"),
                Covenant(0, _bh(seed + 9)),
            ),
        ],
        lock_time=0,
        subnetwork_id=b"\x00" * 20,
        gas=0,
        payload=b"",
        storage_mass=2036 + seed,
    )


def sample_block(seed: int = 1) -> Block:
    return Block(sample_header(seed), [sample_tx(seed), sample_tx(seed + 16, budget=False)])


def _sample_utxo_pairs(seed: int = 1):
    return [
        (
            TransactionOutpoint(_bh(seed + 20), i),
            UtxoEntry(
                amount=1_000 + i,
                script_public_key=ScriptPublicKey(0, b"\x20" + _bh(seed + 21) + b"\xac"),
                block_daa_score=500 + i,
                is_coinbase=(i == 0),
                covenant_id=_bh(seed + 22) if i == 1 else None,
            ),
        )
        for i in range(2)
    ]


def sample_trusted_data() -> TrustedData:
    h = sample_header(40)
    return TrustedData(
        pruning_point=h.hash,
        past_pruning_points=[_bh(41), _bh(42)],
        headers=[h],
        ghostdag={
            h.hash: GhostdagData(
                blue_score=h.blue_score,
                blue_work=h.blue_work,
                selected_parent=_bh(40),
                mergeset_blues=[_bh(40)],
                mergeset_reds=[],
                blues_anticone_sizes={_bh(40): 0},
            )
        },
        statuses={h.hash: "UTXOValid"},
        reach_mergesets={h.hash: [_bh(40)]},
        bodies={h.hash: [sample_tx(44)]},
        daa_excluded={h.hash: {_bh(45)}},
        depth={h.hash: (_bh(46), _bh(47))},
        pruning_samples={h.hash: _bh(48)},
        pp_windows={"daa": [(7, _bh(49))], "median_time": [(3, _bh(50))]},
    )


def sample_smt_chunk() -> dict:
    return {
        "active": True,
        "meta": {
            "lanes_root": _bh(60),
            "pcd": _bh(61),
            "parent_seq_commit": _bh(62),
            "shortcut_block": _bh(63),
            "inactivity_shortcut": _bh(64),
        },
        "offset": 0,
        "lanes": [(_bh(65), _bh(66), 12), (_bh(67), _bh(68), 34)],
        "segment": [sample_header(70)],
        "done": False,
    }


def sample_payloads() -> dict[str, object]:
    """msg_type -> representative payload, covering the whole converter table."""
    n = p2p_node
    return {
        n.MSG_VERSION: {"protocol_version": 10, "network": "simnet", "listen_port": 16111, "id": 0x1122334455667788},
        n.MSG_VERACK: 0,
        MSG_PING: 0x0123456789ABCDEF,
        MSG_PONG: 0x0123456789ABCDF0,
        n.MSG_REJECT: "wrong network",
        n.MSG_REQUEST_ADDRESSES: {},
        n.MSG_ADDRESSES: ["10.0.0.1:16111", "::1:16112"],
        n.MSG_INV_BLOCK: _bh(2),
        n.MSG_REQUEST_BLOCK: [_bh(3), _bh(4)],
        n.MSG_BLOCK: sample_block(1),
        n.MSG_TX: sample_tx(5),
        n.MSG_INV_TXS: [_bh(6)],
        n.MSG_REQUEST_TXS: [_bh(6), _bh(7)],
        n.MSG_REQUEST_HEADERS: _bh(8),
        n.MSG_HEADERS: {"headers": [sample_header(9), sample_header(10)], "done": False, "continuation": _bh(11)},
        n.MSG_REQUEST_PRUNING_PROOF: {},
        n.MSG_PRUNING_PROOF: [[sample_header(12)], [sample_header(13), sample_header(14)]],
        n.MSG_REQUEST_PP_UTXOS: 128,
        n.MSG_PP_UTXO_CHUNK: {"offset": 128, "pairs": _sample_utxo_pairs(1), "done": True},
        n.MSG_IBD_BLOCK_LOCATOR: [_bh(15), _bh(16)],
        n.MSG_REQUEST_ANTIPAST: _bh(17),
        n.MSG_IBD_BLOCKS: {"blocks": [sample_block(18)], "done": False, "continuation": _bh(19)},
        n.MSG_REQUEST_IBD_CHAIN_INFO: {},
        n.MSG_IBD_CHAIN_INFO: {"sink": _bh(20), "sink_blue_work": 0xFEED_0000_1234, "pruning_point": _bh(21)},
        n.MSG_REQUEST_TRUSTED_DATA: {},
        n.MSG_TRUSTED_DATA: sample_trusted_data(),
        n.MSG_REQUEST_PP_SMT: {"pp": _bh(22), "offset": 64},
        n.MSG_PP_SMT_CHUNK: sample_smt_chunk(),
        n.MSG_REQUEST_BLOCK_BODIES: [_bh(23)],
        n.MSG_BLOCK_BODIES: [(_bh(24), [sample_tx(25)])],
    }
