"""gRPC message framing (the tonic layer under the reference's P2P).

The reference node's peers exchange `KaspadMessage`s over a bidirectional
gRPC stream; on the wire each message rides a 5-byte gRPC frame prefix:

    compressed-flag(1) | message-length(4, BIG-endian) | message

(gRPC "Length-Prefixed-Message", the HTTP/2 DATA payload layout).  This
module is that framing over our existing socket transport — an HTTP/2-lite
wire: the stream framing is byte-identical to what tonic puts inside DATA
frames, without the surrounding HTTP/2 connection machinery, which the
transport layer (TCP + reader/writer threads) already provides.

Compression is never used by the reference P2P and is refused here.
"""

from __future__ import annotations

import struct

from kaspa_tpu.p2p.proto.wire_format import ProtoWireError

GRPC_FRAME_OVERHEAD = 5
MAX_GRPC_MESSAGE = 1 << 30  # same bound as the custom wire's MAX_FRAME


def encode_grpc_frame(message: bytes) -> bytes:
    if len(message) > MAX_GRPC_MESSAGE:
        raise ProtoWireError(f"oversized gRPC message {len(message)}")
    return b"\x00" + struct.pack(">I", len(message)) + message


def decode_grpc_prefix(prefix: bytes) -> int:
    """5-byte gRPC prefix -> message length; refuses compressed frames."""
    if len(prefix) != GRPC_FRAME_OVERHEAD:
        raise ProtoWireError(f"short gRPC prefix ({len(prefix)} bytes)")
    if prefix[0] & 0x01:
        raise ProtoWireError("compressed gRPC frames are not supported")
    if prefix[0] & ~0x01:
        raise ProtoWireError(f"reserved gRPC flag bits set ({prefix[0]:#x})")
    (n,) = struct.unpack(">I", prefix[1:5])
    if n > MAX_GRPC_MESSAGE:
        raise ProtoWireError(f"oversized gRPC message {n}")
    return n


def read_grpc_frame(read_exactly) -> bytes:
    """Read one length-prefixed message via ``read_exactly(n) -> bytes``."""
    return read_exactly(decode_grpc_prefix(read_exactly(GRPC_FRAME_OVERHEAD)))
