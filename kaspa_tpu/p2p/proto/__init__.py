"""Reference-compatible protobuf P2P wire (protocol/p2p/proto).

Layers (bottom up):

- ``wire_format``: dependency-free protobuf wire-format engine — varint,
  zigzag, tag/wire-type framing, length-delimited fields, descriptor-driven
  message encode/decode with unknown-field skip.
- ``schema``: the vendored message-schema table mirroring the reference's
  ``messages.proto``/``p2p.proto`` payload set (KaspadMessage oneof).
- ``codec``: model objects (Header/Transaction/Block/TrustedData...) <->
  proto dicts <-> KaspadMessage bytes, plus the tier-version mapping.
- ``framing``: the gRPC-style 5-byte message prefix the reference's tonic
  stack puts around every KaspadMessage on the socket.

The transport binding (``GrpcProtoCodec``) lives in ``p2p/transport.py``
next to the custom-frame codec; both speak to the same flow layer.
"""

from kaspa_tpu.p2p.proto.codec import (  # noqa: F401
    ProtoError,
    decode_kaspad_message,
    encode_kaspad_message,
)
from kaspa_tpu.p2p.proto.framing import (  # noqa: F401
    GRPC_FRAME_OVERHEAD,
    encode_grpc_frame,
    read_grpc_frame,
)
