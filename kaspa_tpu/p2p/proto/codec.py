"""KaspadMessage codec: flow payloads <-> protobuf bytes.

The translation layer between the flow layer's payload shapes (the same
objects `p2p/wire.py` frames with the canonical serde codec) and the
vendored protobuf schema — the role of the From/TryFrom impls in the
reference's `protocol/p2p/src/convert/` tree.

``encode_kaspad_message`` / ``decode_kaspad_message`` are the pure
(bytes in/out) surface the gRPC transport codec wraps; they are also what
the golden-vector fixtures pin.

Version negotiation mapping: our protocol *tiers* (7 = base flows,
8/9 = body-only sync, 10 = Toccata SMT state) map one-to-one onto the
reference's ``VersionMessage.protocolVersion`` field — the reference uses
the same integers for the same flow sets (flows/src/{v7,v8,v10}/mod.rs),
so ``tier_to_wire_version`` is the identity with range clamping, kept as
an explicit seam for the day the numbering diverges.
"""

from __future__ import annotations

import ipaddress

from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.consensus.model.header import Header
from kaspa_tpu.consensus.model.tx import (
    ComputeCommit,
    Covenant,
    ScriptPublicKey,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)
from kaspa_tpu.p2p import wire
from kaspa_tpu.p2p.node import (
    MSG_ADDRESSES,
    MSG_BLOCK,
    MSG_BLOCK_BODIES,
    MSG_HEADERS,
    MSG_IBD_BLOCK_LOCATOR,
    MSG_IBD_BLOCKS,
    MSG_IBD_CHAIN_INFO,
    MSG_INV_BLOCK,
    MSG_INV_TXS,
    MSG_PP_SMT_CHUNK,
    MSG_PP_UTXO_CHUNK,
    MSG_PRUNING_PROOF,
    MSG_REJECT,
    MSG_REQUEST_ADDRESSES,
    MSG_REQUEST_ANTIPAST,
    MSG_REQUEST_BLOCK,
    MSG_REQUEST_BLOCK_BODIES,
    MSG_REQUEST_HEADERS,
    MSG_REQUEST_IBD_CHAIN_INFO,
    MSG_REQUEST_PP_SMT,
    MSG_REQUEST_PP_UTXOS,
    MSG_REQUEST_PRUNING_PROOF,
    MSG_REQUEST_TRUSTED_DATA,
    MSG_REQUEST_TXS,
    MSG_TRUSTED_DATA,
    MSG_TX,
    MSG_VERACK,
    MSG_VERSION,
    MIN_PROTOCOL_VERSION,
    PROTOCOL_VERSION,
)
from kaspa_tpu.p2p.proto import schema
from kaspa_tpu.p2p.proto.wire_format import ProtoWireError, decode_message, encode_message
from kaspa_tpu.p2p.wire import MSG_PING, MSG_PONG

USER_AGENT = "/kaspa-tpu:0.1/"


class ProtoError(ProtoWireError):
    """Semantically invalid KaspadMessage (unknown payload, bad mapping)."""


# -- tier <-> reference protocolVersion mapping ----------------------------


def tier_to_wire_version(tier: int) -> int:
    """Our flow tier -> VersionMessage.protocolVersion (identity today)."""
    return max(MIN_PROTOCOL_VERSION, min(int(tier), PROTOCOL_VERSION))


def wire_version_to_tier(version: int) -> int:
    """VersionMessage.protocolVersion -> our flow tier.  Future reference
    versions clamp to the highest tier we implement (the handshake then
    negotiates min(local, peer) exactly like the custom wire)."""
    return max(0, min(int(version), PROTOCOL_VERSION))


# -- leaf converters -------------------------------------------------------


def _h(h: bytes) -> dict:
    return {"bytes": h}


def _uh(d: dict | None) -> bytes:
    return d["bytes"] if d else b""


def _work_to_bytes(w: int) -> bytes:
    """Uint192 -> minimal big-endian bytes (header.rs blue_work wire form)."""
    return w.to_bytes((w.bit_length() + 7) // 8, "big") if w else b""


def _work_from_bytes(b: bytes) -> int:
    return int.from_bytes(b, "big")


def _ip_to_bytes(ip: str) -> bytes:
    """IP string -> 16-byte address (IPv4 mapped into ::ffff:0:0/96, the
    reference NetAddress form).  Non-parseable hosts (DNS names from the
    address book) fall back to raw UTF-8; the decoder disambiguates by
    trying the 16-byte form first."""
    try:
        addr = ipaddress.ip_address(ip)
    except ValueError:
        return ip.encode("utf-8")
    if addr.version == 4:
        addr = ipaddress.IPv6Address(b"\x00" * 10 + b"\xff\xff" + addr.packed)
    return addr.packed


def _ip_from_bytes(raw: bytes) -> str:
    if len(raw) == 16:
        addr = ipaddress.IPv6Address(raw)
        mapped = addr.ipv4_mapped
        return str(mapped) if mapped is not None else str(addr)
    if len(raw) == 4:
        return str(ipaddress.IPv4Address(raw))
    return raw.decode("utf-8", "replace")


def header_to_proto(h: Header) -> dict:
    return {
        "version": h.version,
        "hashMerkleRoot": _h(h.hash_merkle_root),
        "acceptedIdMerkleRoot": _h(h.accepted_id_merkle_root),
        "utxoCommitment": _h(h.utxo_commitment),
        "timestamp": h.timestamp,
        "bits": h.bits,
        "nonce": h.nonce,
        "daaScore": h.daa_score,
        "blueWork": _work_to_bytes(h.blue_work),
        "parents": [{"parentHashes": [_h(p) for p in level]} for level in h.parents_by_level],
        "blueScore": h.blue_score,
        "pruningPoint": _h(h.pruning_point),
    }


def proto_to_header(d: dict) -> Header:
    return Header(
        version=d["version"],
        parents_by_level=[[_uh(p) for p in level["parentHashes"]] for level in d["parents"]],
        hash_merkle_root=_uh(d["hashMerkleRoot"]),
        accepted_id_merkle_root=_uh(d["acceptedIdMerkleRoot"]),
        utxo_commitment=_uh(d["utxoCommitment"]),
        timestamp=d["timestamp"],
        bits=d["bits"],
        nonce=d["nonce"],
        daa_score=d["daaScore"],
        blue_work=_work_from_bytes(d["blueWork"]),
        blue_score=d["blueScore"],
        pruning_point=_uh(d["pruningPoint"]),
    )


def tx_to_proto(tx: Transaction) -> dict:
    inputs = []
    for i in tx.inputs:
        d = {
            "previousOutpoint": {
                "transactionId": _h(i.previous_outpoint.transaction_id),
                "index": i.previous_outpoint.index,
            },
            "signatureScript": i.signature_script,
            "sequence": i.sequence,
        }
        if i.compute_commit.kind == "budget":
            d["computeBudget"] = i.compute_commit.value
        else:
            d["sigOpCount"] = i.compute_commit.value
        inputs.append(d)
    outputs = []
    for o in tx.outputs:
        d = {
            "value": o.value,
            "scriptPublicKey": {"script": o.script_public_key.script, "version": o.script_public_key.version},
        }
        if o.covenant is not None:
            d["covenant"] = {
                "authorizingInput": o.covenant.authorizing_input,
                "covenantId": o.covenant.covenant_id,
            }
        outputs.append(d)
    return {
        "version": tx.version,
        "inputs": inputs,
        "outputs": outputs,
        "lockTime": tx.lock_time,
        "subnetworkId": {"bytes": tx.subnetwork_id},
        "gas": tx.gas,
        "payload": tx.payload,
        "mass": tx.storage_mass,
    }


def proto_to_tx(d: dict) -> Transaction:
    version = d["version"]
    inputs = []
    for i in d["inputs"]:
        op = TransactionOutpoint(_uh(i["previousOutpoint"]["transactionId"]), i["previousOutpoint"]["index"])
        if ComputeCommit.version_expects_compute_budget_field(version):
            cc = ComputeCommit.budget(i["computeBudget"])
        else:
            cc = ComputeCommit.sigops(i["sigOpCount"])
        inputs.append(TransactionInput(op, i["signatureScript"], i["sequence"], cc))
    outputs = []
    for o in d["outputs"]:
        spk = ScriptPublicKey(o["scriptPublicKey"]["version"], o["scriptPublicKey"]["script"])
        cov = None
        if o["covenant"] is not None:
            cov = Covenant(o["covenant"]["authorizingInput"], o["covenant"]["covenantId"])
        outputs.append(TransactionOutput(o["value"], spk, cov))
    return Transaction(
        version,
        inputs,
        outputs,
        d["lockTime"],
        _uh(d["subnetworkId"]),
        d["gas"],
        d["payload"],
        storage_mass=d["mass"],
    )


def block_to_proto(b: Block) -> dict:
    return {"header": header_to_proto(b.header), "transactions": [tx_to_proto(t) for t in b.transactions]}


def proto_to_block(d: dict) -> Block:
    return Block(proto_to_header(d["header"]), [proto_to_tx(t) for t in d["transactions"]])


def _utxo_entry_to_proto(e: UtxoEntry) -> dict:
    d = {
        "amount": e.amount,
        "scriptPublicKey": {"script": e.script_public_key.script, "version": e.script_public_key.version},
        "blockDaaScore": e.block_daa_score,
        "isCoinbase": e.is_coinbase,
    }
    if e.covenant_id is not None:
        d["covenantId"] = e.covenant_id
    return d


def _proto_to_utxo_entry(d: dict) -> UtxoEntry:
    return UtxoEntry(
        amount=d["amount"],
        script_public_key=ScriptPublicKey(d["scriptPublicKey"]["version"], d["scriptPublicKey"]["script"]),
        block_daa_score=d["blockDaaScore"],
        is_coinbase=d["isCoinbase"],
        covenant_id=d["covenantId"] or None,
    )


# -- per-payload converters ------------------------------------------------
# each entry: msg_type -> (oneof_key, payload -> proto dict, proto dict -> payload)


def _enc_version(p: dict) -> dict:
    d = {
        "protocolVersion": tier_to_wire_version(p["protocol_version"]),
        "id": int(p.get("id", 0)).to_bytes(16, "little"),
        "userAgent": USER_AGENT,
        "network": "kaspa-" + p["network"],
    }
    if p.get("listen_port"):
        d["address"] = {"port": p["listen_port"]}
    return d


def _dec_version(d: dict) -> dict:
    network = d["network"]
    if network.startswith("kaspa-"):
        network = network[len("kaspa-") :]
    return {
        "protocol_version": wire_version_to_tier(d["protocolVersion"]),
        "network": network,
        "listen_port": d["address"]["port"] if d["address"] else 0,
        "id": int.from_bytes(d["id"][:16], "little"),
    }


def _enc_hash_list(hashes, key="hashes"):
    return {key: [_h(x) for x in hashes]}


def _dec_hash_list(d, key="hashes"):
    return [_uh(x) for x in d[key]]


def _enc_headers_chunk(p: dict) -> dict:
    return {
        "blockHeaders": [header_to_proto(h) for h in p["headers"]],
        "done": p["done"],
        "continuation": p["continuation"],
    }


def _dec_headers_chunk(d: dict) -> dict:
    return {
        "headers": [proto_to_header(h) for h in d["blockHeaders"]],
        "done": d["done"],
        "continuation": d["continuation"],
    }


def _enc_ibd_chunk(p: dict) -> dict:
    return {
        "blocks": [block_to_proto(b) for b in p["blocks"]],
        "done": p["done"],
        "continuation": p["continuation"],
    }


def _dec_ibd_chunk(d: dict) -> dict:
    return {
        "blocks": [proto_to_block(b) for b in d["blocks"]],
        "done": d["done"],
        "continuation": d["continuation"],
    }


def _enc_utxo_chunk(p: dict) -> dict:
    return {
        "outpointAndUtxoEntryPairs": [
            {
                "outpoint": {"transactionId": _h(op.transaction_id), "index": op.index},
                "utxoEntry": _utxo_entry_to_proto(e),
            }
            for op, e in p["pairs"]
        ],
        "offset": p["offset"],
        "done": p["done"],
    }


def _dec_utxo_chunk(d: dict) -> dict:
    pairs = []
    for pair in d["outpointAndUtxoEntryPairs"]:
        op = TransactionOutpoint(_uh(pair["outpoint"]["transactionId"]), pair["outpoint"]["index"])
        pairs.append((op, _proto_to_utxo_entry(pair["utxoEntry"])))
    return {"offset": d["offset"], "pairs": pairs, "done": d["done"]}


def _enc_proof(levels) -> dict:
    return {"headers": [{"headers": [header_to_proto(h) for h in level]} for level in levels]}


def _dec_proof(d: dict):
    return [[proto_to_header(h) for h in level["headers"]] for level in d["headers"]]


def _enc_addresses(items) -> dict:
    out = []
    for s in items:
        host, port = s.rsplit(":", 1)
        out.append({"ip": _ip_to_bytes(host), "port": int(port)})
    return {"addressList": out}


def _dec_addresses(d: dict) -> list:
    return [f"{_ip_from_bytes(a['ip'])}:{a['port']}" for a in d["addressList"]]


def _enc_bodies(items) -> dict:
    return {
        "entries": [
            {"hash": h, "transactions": [tx_to_proto(t) for t in txs]} for h, txs in items
        ]
    }


def _dec_bodies(d: dict) -> list:
    return [(e["hash"], [proto_to_tx(t) for t in e["transactions"]]) for e in d["entries"]]


_CONVERTERS = {
    MSG_VERSION: ("version", _enc_version, _dec_version),
    # the reference verack carries no payload; the custom wire's advertised
    # version rides the version message instead, so decode yields 0 (unused
    # by the flow layer)
    MSG_VERACK: ("verack", lambda _p: {}, lambda _d: 0),
    MSG_PING: ("ping", lambda n: {"nonce": n}, lambda d: d["nonce"]),
    MSG_PONG: ("pong", lambda n: {"nonce": n}, lambda d: d["nonce"]),
    MSG_REJECT: ("reject", lambda s: {"reason": s}, lambda d: d["reason"]),
    MSG_REQUEST_ADDRESSES: ("requestAddresses", lambda _p: {}, lambda _d: {}),
    MSG_ADDRESSES: ("addresses", _enc_addresses, _dec_addresses),
    MSG_INV_BLOCK: ("invRelayBlock", lambda h: {"hash": _h(h)}, lambda d: _uh(d["hash"])),
    MSG_REQUEST_BLOCK: ("requestRelayBlocks", _enc_hash_list, _dec_hash_list),
    MSG_BLOCK: ("block", block_to_proto, proto_to_block),
    MSG_TX: ("transaction", tx_to_proto, proto_to_tx),
    MSG_INV_TXS: (
        "invTransactions",
        lambda ids: _enc_hash_list(ids, "ids"),
        lambda d: _dec_hash_list(d, "ids"),
    ),
    MSG_REQUEST_TXS: (
        "requestTransactions",
        lambda ids: _enc_hash_list(ids, "ids"),
        lambda d: _dec_hash_list(d, "ids"),
    ),
    MSG_REQUEST_HEADERS: ("requestHeaders", lambda h: {"lowHash": _h(h)}, lambda d: _uh(d["lowHash"])),
    MSG_HEADERS: ("blockHeaders", _enc_headers_chunk, _dec_headers_chunk),
    MSG_REQUEST_PRUNING_PROOF: ("requestPruningPointProof", lambda _p: {}, lambda _d: {}),
    MSG_PRUNING_PROOF: ("pruningPointProof", _enc_proof, _dec_proof),
    MSG_REQUEST_PP_UTXOS: (
        "requestPruningPointUTXOSet",
        lambda offset: {"offset": int(offset)},
        lambda d: d["offset"],
    ),
    MSG_PP_UTXO_CHUNK: ("pruningPointUtxoSetChunk", _enc_utxo_chunk, _dec_utxo_chunk),
    MSG_IBD_BLOCK_LOCATOR: (
        "ibdChainBlockLocator",
        lambda hashes: _enc_hash_list(hashes, "blockLocatorHashes"),
        lambda d: _dec_hash_list(d, "blockLocatorHashes"),
    ),
    MSG_REQUEST_ANTIPAST: ("requestAnticone", lambda h: {"blockHash": _h(h)}, lambda d: _uh(d["blockHash"])),
    MSG_IBD_BLOCKS: ("ibdBlocksChunk", _enc_ibd_chunk, _dec_ibd_chunk),
    MSG_REQUEST_IBD_CHAIN_INFO: ("requestIbdChainInfo", lambda _p: {}, lambda _d: {}),
    MSG_IBD_CHAIN_INFO: (
        "ibdChainInfo",
        lambda p: {
            "sink": p["sink"],
            "sinkBlueWork": _work_to_bytes(p["sink_blue_work"]),
            "pruningPoint": p["pruning_point"],
        },
        lambda d: {
            "sink": d["sink"],
            "sink_blue_work": _work_from_bytes(d["sinkBlueWork"]),
            "pruning_point": d["pruningPoint"],
        },
    ),
    MSG_REQUEST_TRUSTED_DATA: ("requestTrustedData", lambda _p: {}, lambda _d: {}),
    # blob envelopes reuse the canonical serde payload codecs from wire.py
    MSG_TRUSTED_DATA: (
        "trustedData",
        lambda td: {"blob": wire._enc_trusted(td)},
        lambda d: wire._dec_trusted(d["blob"]),
    ),
    MSG_REQUEST_PP_SMT: (
        "requestPruningPointSmtState",
        lambda p: {"pruningPointHash": p["pp"], "offset": p["offset"]},
        lambda d: {"pp": d["pruningPointHash"], "offset": d["offset"]},
    ),
    MSG_PP_SMT_CHUNK: (
        "pruningPointSmtStateChunk",
        lambda p: {"blob": wire._enc_smt_chunk(p)},
        lambda d: wire._dec_smt_chunk(d["blob"]),
    ),
    MSG_REQUEST_BLOCK_BODIES: ("requestBlockBodies", _enc_hash_list, _dec_hash_list),
    MSG_BLOCK_BODIES: ("blockBodies", _enc_bodies, _dec_bodies),
}

_KEY_TO_MSG = {key: (msg_type, dec) for msg_type, (key, _enc, dec) in _CONVERTERS.items()}

# every oneof field number declared in the schema must have a converter —
# asserted at import so schema/converter drift fails loudly, not per-message
_ONEOF_KEYS = {f[0] for f in schema.KASPAD_MESSAGE["fields"].values()}
assert _ONEOF_KEYS == set(_KEY_TO_MSG), (
    f"schema/converter drift: {sorted(_ONEOF_KEYS.symmetric_difference(_KEY_TO_MSG))}"
)


def encode_kaspad_message(msg_type: str, payload) -> bytes:
    """(flow msg_type, payload) -> KaspadMessage protobuf bytes."""
    conv = _CONVERTERS.get(msg_type)
    if conv is None:
        raise ProtoError(f"no protobuf mapping for message type {msg_type!r}")
    key, enc, _dec = conv
    return encode_message(schema.KASPAD_MESSAGE, {key: enc(payload)})


def decode_kaspad_message(data: bytes) -> tuple[str, object]:
    """KaspadMessage protobuf bytes -> (flow msg_type, payload)."""
    msg = decode_message(schema.KASPAD_MESSAGE, data)
    for key, value in msg.items():
        if value is not None:
            msg_type, dec = _KEY_TO_MSG[key]
            return msg_type, dec(value)
    raise ProtoError("KaspadMessage carries no known payload (empty or extension-only)")
