"""Protobuf wire-format engine (dependency-free).

The byte-level half of the reference's prost-generated codecs
(protocol/p2p/proto compiled by tonic-build): base-128 varints, zigzag
signed scalars, the tag = (field_number << 3 | wire_type) framing, and
length-delimited nested messages/bytes/strings — implemented directly so
the container needs no protobuf runtime.

Messages are encoded from / decoded into plain dicts, driven by the
descriptors in ``schema.py``.  Encoding follows proto3 semantics:

- fields are emitted in ascending field-number order (deterministic bytes,
  required for the golden-vector fixtures),
- default values (0, "", b"", False, empty list) are skipped,
- repeated message/bytes fields are emitted as one tagged record each.

Decoding skips unknown fields by wire type (the mechanism that lets a
reference peer add fields without breaking us, and lets us ride extension
fields past a reference decoder), counting skips in an observability
counter.
"""

from __future__ import annotations

import struct

from kaspa_tpu.observability.core import REGISTRY

_UNKNOWN_FIELDS = REGISTRY.counter(
    "p2p_proto_unknown_fields_skipped", help="protobuf fields skipped by the unknown-field rule"
)


class ProtoWireError(Exception):
    """Malformed protobuf bytes (truncation, bad wire type, overlong varint)."""


# wire types (protobuf encoding spec)
WT_VARINT = 0
WT_I64 = 1
WT_LEN = 2
WT_I32 = 5

MAX_VARINT_BYTES = 10  # 64-bit varints never exceed 10 bytes


# -- varint / zigzag -------------------------------------------------------


def encode_varint(v: int) -> bytes:
    if v < 0:
        # proto3 negative int32/int64 values are sign-extended to 64 bits
        v &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(data: bytes, pos: int) -> tuple[int, int]:
    """-> (value, new_pos); raises on truncation or overlong encoding."""
    result = 0
    shift = 0
    start = pos
    while True:
        if pos >= len(data):
            raise ProtoWireError("truncated varint")
        if pos - start >= MAX_VARINT_BYTES:
            raise ProtoWireError("varint exceeds 10 bytes")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def zigzag_encode(v: int) -> int:
    """Signed -> unsigned zigzag (sint32/sint64 scalars)."""
    return (v << 1) ^ (v >> 63) if v < 0 else v << 1


def zigzag_decode(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


# -- tags ------------------------------------------------------------------


def encode_tag(field_number: int, wire_type: int) -> bytes:
    return encode_varint((field_number << 3) | wire_type)


def decode_tag(data: bytes, pos: int) -> tuple[int, int, int]:
    """-> (field_number, wire_type, new_pos)."""
    tag, pos = decode_varint(data, pos)
    field_number, wire_type = tag >> 3, tag & 0x07
    if field_number == 0:
        raise ProtoWireError("field number 0 is reserved")
    return field_number, wire_type, pos


def skip_field(data: bytes, pos: int, wire_type: int) -> int:
    """Advance past one unknown field's value (the unknown-field rule)."""
    _UNKNOWN_FIELDS.inc()
    if wire_type == WT_VARINT:
        _, pos = decode_varint(data, pos)
        return pos
    if wire_type == WT_I64:
        if pos + 8 > len(data):
            raise ProtoWireError("truncated fixed64 field")
        return pos + 8
    if wire_type == WT_LEN:
        n, pos = decode_varint(data, pos)
        if pos + n > len(data):
            raise ProtoWireError("truncated length-delimited field")
        return pos + n
    if wire_type == WT_I32:
        if pos + 4 > len(data):
            raise ProtoWireError("truncated fixed32 field")
        return pos + 4
    raise ProtoWireError(f"unsupported wire type {wire_type} (groups are not emitted by proto3)")


# -- descriptor-driven message encode/decode -------------------------------

# scalar kinds understood by the engine; "message" fields carry a nested
# descriptor.  sint64 is the zigzag lane; fixed32/fixed64 round the engine
# out for schema evolution even though the vendored set is varint/LEN-only.
_VARINT_KINDS = frozenset({"uint32", "uint64", "int64", "bool", "sint64"})


def _encode_scalar(kind: str, value) -> bytes:
    if kind == "bool":
        return encode_varint(1 if value else 0)
    if kind == "sint64":
        return encode_varint(zigzag_encode(int(value)))
    if kind in ("uint32", "uint64", "int64"):
        return encode_varint(int(value))
    if kind == "bytes":
        return encode_varint(len(value)) + bytes(value)
    if kind == "string":
        raw = value.encode("utf-8")
        return encode_varint(len(raw)) + raw
    if kind == "fixed64":
        return struct.pack("<Q", int(value))
    if kind == "fixed32":
        return struct.pack("<I", int(value))
    raise ProtoWireError(f"unknown scalar kind {kind!r}")


def _is_default(kind: str, value) -> bool:
    if kind in ("bytes", "string"):
        return len(value) == 0
    if kind == "bool":
        return not value
    return value == 0


def encode_message(descriptor: dict, msg: dict) -> bytes:
    """Encode a dict against a schema descriptor -> deterministic bytes."""
    out = bytearray()
    for number in sorted(descriptor["fields"]):
        name, kind, repeated, nested = descriptor["fields"][number]
        value = msg.get(name)
        if value is None:
            continue
        if repeated:
            values = value
        else:
            values = (value,)
        for v in values:
            if kind == "message":
                body = encode_message(nested, v)
                out += encode_tag(number, WT_LEN)
                out += encode_varint(len(body))
                out += body
            elif kind in _VARINT_KINDS:
                if not repeated and _is_default(kind, v):
                    continue  # proto3: scalar defaults are not emitted
                out += encode_tag(number, WT_VARINT)
                out += _encode_scalar(kind, v)
            elif kind == "fixed64":
                out += encode_tag(number, WT_I64)
                out += _encode_scalar(kind, v)
            elif kind == "fixed32":
                out += encode_tag(number, WT_I32)
                out += _encode_scalar(kind, v)
            else:  # bytes / string
                if not repeated and _is_default(kind, v):
                    continue
                out += encode_tag(number, WT_LEN)
                out += _encode_scalar(kind, v)
    return bytes(out)


def _decode_scalar(kind: str, data: bytes, pos: int, wire_type: int):
    if kind in _VARINT_KINDS:
        if wire_type != WT_VARINT:
            raise ProtoWireError(f"wire type {wire_type} for varint field")
        v, pos = decode_varint(data, pos)
        if kind == "bool":
            return bool(v), pos
        if kind == "sint64":
            return zigzag_decode(v), pos
        if kind == "int64" and v >= 1 << 63:
            return v - (1 << 64), pos  # sign-extend
        if kind == "uint32":
            return v & 0xFFFFFFFF, pos
        return v, pos
    if kind in ("bytes", "string"):
        if wire_type != WT_LEN:
            raise ProtoWireError(f"wire type {wire_type} for length-delimited field")
        n, pos = decode_varint(data, pos)
        if pos + n > len(data):
            raise ProtoWireError("truncated length-delimited field")
        raw = data[pos : pos + n]
        return (raw.decode("utf-8") if kind == "string" else raw), pos + n
    if kind == "fixed64":
        if wire_type != WT_I64 or pos + 8 > len(data):
            raise ProtoWireError("bad fixed64 field")
        return struct.unpack_from("<Q", data, pos)[0], pos + 8
    if kind == "fixed32":
        if wire_type != WT_I32 or pos + 4 > len(data):
            raise ProtoWireError("bad fixed32 field")
        return struct.unpack_from("<I", data, pos)[0], pos + 4
    raise ProtoWireError(f"unknown scalar kind {kind!r}")


def decode_message(descriptor: dict, data: bytes) -> dict:
    """Decode bytes against a descriptor -> dict.

    Every declared field gets a key: scalars default per proto3, repeated
    fields default to [], absent sub-messages to None — so the model layer
    never needs ``.get`` chains.  Unknown fields are skipped.
    """
    msg: dict = {}
    for number in descriptor["fields"]:
        name, kind, repeated, _nested = descriptor["fields"][number]
        if repeated:
            msg[name] = []
        elif kind == "message":
            msg[name] = None
        elif kind in ("bytes",):
            msg[name] = b""
        elif kind == "string":
            msg[name] = ""
        elif kind == "bool":
            msg[name] = False
        else:
            msg[name] = 0
    pos = 0
    while pos < len(data):
        number, wire_type, pos = decode_tag(data, pos)
        field = descriptor["fields"].get(number)
        if field is None:
            pos = skip_field(data, pos, wire_type)
            continue
        name, kind, repeated, nested = field
        if kind == "message":
            if wire_type != WT_LEN:
                raise ProtoWireError(f"wire type {wire_type} for message field {name}")
            n, pos = decode_varint(data, pos)
            if pos + n > len(data):
                raise ProtoWireError(f"truncated message field {name}")
            value = decode_message(nested, data[pos : pos + n])
            pos += n
        else:
            value, pos = _decode_scalar(kind, data, pos, wire_type)
        if repeated:
            msg[name].append(value)
        else:
            msg[name] = value
    return msg
