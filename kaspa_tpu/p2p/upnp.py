"""UPnP IGD port mapping + lease extender.

The role of the reference's igd-backed mapping
(components/addressmanager/src/lib.rs:30-34 UPNP_DEADLINE_SEC/
UPNP_EXTEND_PERIOD/UPNP_REGISTRATION_NAME, configure_port_mapping,
port_mapping_extender.rs Extender): discover the internet gateway over
SSDP, learn the external IP, register a TCP mapping for the P2P listen
port with a short lease, and re-register on a half-lease tick so the
mapping dies soon after the node does.

Pure stdlib (UDP SSDP + HTTP SOAP); every network touch has a short
timeout and the whole feature fails soft — a node without a cooperative
gateway just runs unmapped, exactly like the reference.
"""

from __future__ import annotations

import http.client
import re
import socket
import threading
import urllib.parse
import urllib.request

from kaspa_tpu.core.log import get_logger

log = get_logger("p2p.upnp")

UPNP_DEADLINE_SEC = 2 * 60
UPNP_EXTEND_PERIOD = UPNP_DEADLINE_SEC // 2
UPNP_REGISTRATION_NAME = "kaspa-tpu"

SSDP_ADDR = ("239.255.255.250", 1900)
_SEARCH_TARGETS = (
    "urn:schemas-upnp-org:device:InternetGatewayDevice:1",
    "urn:schemas-upnp-org:service:WANIPConnection:1",
)
_SERVICE_TYPES = (
    "urn:schemas-upnp-org:service:WANIPConnection:1",
    "urn:schemas-upnp-org:service:WANPPPConnection:1",
)


class UpnpError(Exception):
    pass


class Gateway:
    """One discovered IGD control endpoint."""

    def __init__(self, control_url: str, service_type: str):
        self.control_url = control_url
        self.service_type = service_type

    def _soap(self, action: str, body_args: str, timeout: float = 5.0) -> str:
        u = urllib.parse.urlsplit(self.control_url)
        envelope = (
            '<?xml version="1.0"?>'
            '<s:Envelope xmlns:s="http://schemas.xmlsoap.org/soap/envelope/" '
            's:encodingStyle="http://schemas.xmlsoap.org/soap/encoding/">'
            f'<s:Body><u:{action} xmlns:u="{self.service_type}">{body_args}</u:{action}>'
            "</s:Body></s:Envelope>"
        )
        conn = http.client.HTTPConnection(u.hostname, u.port or 80, timeout=timeout)
        try:
            conn.request(
                "POST",
                u.path or "/",
                body=envelope.encode(),
                headers={
                    "Content-Type": 'text/xml; charset="utf-8"',
                    "SOAPAction": f'"{self.service_type}#{action}"',
                },
            )
            resp = conn.getresponse()
            data = resp.read().decode("utf-8", "replace")
            if resp.status != 200:
                raise UpnpError(f"{action} failed: HTTP {resp.status}: {data[:200]}")
            return data
        finally:
            conn.close()

    def get_external_ip(self) -> str:
        data = self._soap("GetExternalIPAddress", "")
        m = re.search(r"<NewExternalIPAddress>([^<]+)</NewExternalIPAddress>", data)
        if not m:
            raise UpnpError("gateway returned no external IP")
        return m.group(1).strip()

    def add_port_mapping(
        self,
        external_port: int,
        internal_ip: str,
        internal_port: int,
        lease_sec: int = UPNP_DEADLINE_SEC,
        description: str = UPNP_REGISTRATION_NAME,
        protocol: str = "TCP",
    ) -> None:
        self._soap(
            "AddPortMapping",
            "<NewRemoteHost></NewRemoteHost>"
            f"<NewExternalPort>{external_port}</NewExternalPort>"
            f"<NewProtocol>{protocol}</NewProtocol>"
            f"<NewInternalPort>{internal_port}</NewInternalPort>"
            f"<NewInternalClient>{internal_ip}</NewInternalClient>"
            "<NewEnabled>1</NewEnabled>"
            f"<NewPortMappingDescription>{description}</NewPortMappingDescription>"
            f"<NewLeaseDuration>{lease_sec}</NewLeaseDuration>",
        )

    def delete_port_mapping(self, external_port: int, protocol: str = "TCP") -> None:
        self._soap(
            "DeletePortMapping",
            "<NewRemoteHost></NewRemoteHost>"
            f"<NewExternalPort>{external_port}</NewExternalPort>"
            f"<NewProtocol>{protocol}</NewProtocol>",
        )


def discover_gateway(timeout: float = 3.0, ssdp_addr=SSDP_ADDR) -> Gateway:
    """SSDP M-SEARCH for an IGD, then resolve its WAN control URL from the
    device description document."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.settimeout(timeout)
    location = None
    try:
        for target in _SEARCH_TARGETS:
            msg = (
                "M-SEARCH * HTTP/1.1\r\n"
                f"HOST: {ssdp_addr[0]}:{ssdp_addr[1]}\r\n"
                'MAN: "ssdp:discover"\r\n'
                "MX: 2\r\n"
                f"ST: {target}\r\n\r\n"
            )
            try:
                sock.sendto(msg.encode(), ssdp_addr)
                data, _peer = sock.recvfrom(4096)
            except (socket.timeout, OSError):
                continue
            m = re.search(rb"(?im)^location:\s*(\S+)", data)
            if m:
                location = m.group(1).decode()
                break
    finally:
        sock.close()
    if location is None:
        raise UpnpError("no internet gateway answered SSDP discovery")

    with urllib.request.urlopen(location, timeout=timeout) as resp:
        desc = resp.read().decode("utf-8", "replace")
    base = urllib.parse.urlsplit(location)
    for service_type in _SERVICE_TYPES:
        # the serviceType and its controlURL live in the same <service> block
        pat = (
            r"<service>(?:(?!</service>).)*?"
            + re.escape(service_type)
            + r"(?:(?!</service>).)*?<controlURL>([^<]+)</controlURL>"
        )
        m = re.search(pat, desc, re.S)
        if m:
            control = m.group(1).strip()
            if not control.startswith("http"):
                control = f"{base.scheme}://{base.netloc}{control if control.startswith('/') else '/' + control}"
            return Gateway(control, service_type)
    raise UpnpError("gateway description exposes no WAN connection service")


class PortMappingExtender:
    """Re-registers the mapping every half-lease until stopped
    (port_mapping_extender.rs Extender::worker)."""

    def __init__(
        self,
        gateway: Gateway,
        external_port: int,
        internal_ip: str,
        internal_port: int,
        period_sec: float = UPNP_EXTEND_PERIOD,
        lease_sec: int = UPNP_DEADLINE_SEC,
    ):
        self.gateway = gateway
        self.external_port = external_port
        self.internal_ip = internal_ip
        self.internal_port = internal_port
        self.period_sec = period_sec
        self.lease_sec = lease_sec
        self.extend_count = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True, name="upnp-extender")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.period_sec):
            try:
                self.gateway.add_port_mapping(
                    self.external_port, self.internal_ip, self.internal_port, self.lease_sec
                )
                self.extend_count += 1
                log.trace("extended external port mapping %d", self.external_port)
            except Exception as e:  # noqa: BLE001 - keep extending on transient errors
                log.warn("extend external ip mapping err: %s", e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        try:
            self.gateway.delete_port_mapping(self.external_port)
        except Exception:  # noqa: BLE001 - gateway may be gone on shutdown
            pass


def configure_port_mapping(
    listen_port: int, timeout: float = 3.0, ssdp_addr=SSDP_ADDR
) -> tuple[str, PortMappingExtender]:
    """Discover the gateway, map `listen_port`, return (external_ip,
    running extender) — the reference's configure_port_mapping.  Raises
    UpnpError when no cooperative gateway exists (callers fail soft)."""
    gw = discover_gateway(timeout=timeout, ssdp_addr=ssdp_addr)
    external_ip = gw.get_external_ip()
    # the local address the gateway should forward to: the interface that
    # routes toward the gateway
    probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        probe.connect((urllib.parse.urlsplit(gw.control_url).hostname, 1))
        internal_ip = probe.getsockname()[0]
    finally:
        probe.close()
    gw.add_port_mapping(listen_port, internal_ip, listen_port)
    extender = PortMappingExtender(gw, listen_port, internal_ip, listen_port)
    extender.start()
    log.info(
        "UPnP mapping established: %s:%d -> %s:%d (lease %ds)",
        external_ip, listen_port, internal_ip, listen_port, UPNP_DEADLINE_SEC,
    )
    return external_ip, extender
