"""In-process P2P: routers, flows, and block/tx relay between nodes.

Reference: protocol/p2p (Adaptor/Router/Hub over tonic gRPC, ~60 payload
types) and protocol/flows (one task per flow per peer: handshake, block
relay with orphan resolution, tx relay, IBD).  This round models the flow
layer over an in-process transport — the same peer/message/flow shapes,
synchronous delivery — matching the reference's own in-process daemon
integration strategy (testing/integration/src/common/daemon.rs).  The
tonic-equivalent wire transport (C++ gRPC/asio) binds underneath in a
later milestone without changing the flow logic.

Messages are (type, payload) tuples; types mirror p2p.proto payload names.
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass, field
from time import monotonic as _monotonic

from kaspa_tpu.consensus.consensus import Consensus, RuleError
from kaspa_tpu.consensus.stores import StatusesStore
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.mempool import MiningManager
from kaspa_tpu.mempool.mempool import MempoolError
from kaspa_tpu.observability import flight, trace
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.utils.sync import LockCtx

# p2p.proto payload types modeled this round
MSG_VERSION = "version"
MSG_VERACK = "verack"
MSG_INV_BLOCK = "invrelayblock"
MSG_REQUEST_BLOCK = "requestrelayblocks"
MSG_BLOCK = "block"
MSG_INV_TXS = "invtransactions"
MSG_REQUEST_TXS = "requesttransactions"
MSG_TX = "transaction"
MSG_IBD_BLOCKS = "ibdblocks"
# proof-based IBD (flows/src/ibd/flow.rs negotiate + headers-proof path)
MSG_REQUEST_IBD_CHAIN_INFO = "requestibdchaininfo"
MSG_IBD_CHAIN_INFO = "ibdchaininfo"
MSG_REQUEST_PRUNING_PROOF = "requestpruningpointproof"
MSG_PRUNING_PROOF = "pruningpointproof"
MSG_REQUEST_TRUSTED_DATA = "requestpruningpointtrusteddata"
MSG_TRUSTED_DATA = "pruningpointtrusteddata"
MSG_REQUEST_PP_UTXOS = "requestpruningpointutxoset"
MSG_PP_UTXO_CHUNK = "pruningpointutxosetchunk"
# KIP-21 lane-state sync (flows/src/ibd/flow.rs:145-150 sync_new_smt_state)
MSG_REQUEST_PP_SMT = "requestpruningpointsmtstate"
MSG_PP_SMT_CHUNK = "pruningpointsmtstatechunk"
# locator sync negotiation (flows/src/ibd/negotiate.rs + sync/mod.rs)
MSG_IBD_BLOCK_LOCATOR = "ibdblocklocator"
MSG_REQUEST_ANTIPAST = "requestantipast"

IBD_BATCH_SIZE = 512  # blocks per IBD chunk (ibd/flow.rs IBD_BATCH_SIZE shape)
# address exchange (flows/src/v7/address.rs)
MSG_REQUEST_ADDRESSES = "requestaddresses"
MSG_ADDRESSES = "addresses"

PP_UTXO_CHUNK_SIZE = 4096  # entries per chunk (ibd/flow.rs utxo chunking)
PP_SMT_CHUNK_SIZE = 4096  # lanes/anchors per chunk (ibd SMT_CHUNK_SIZE role)

# v8 body-only sync (flows/src/v8/request_block_bodies.rs): bodies for
# blocks whose headers the requester already holds
MSG_REQUEST_BLOCK_BODIES = "requestblockbodies"
MSG_BLOCK_BODIES = "blockbodies"
# headers-first sync (request_headers.rs RequestHeaders/BlockHeaders):
# stream headers above a chain anchor, bodies follow via the v8 flow
MSG_REQUEST_HEADERS = "requestheaders"
MSG_HEADERS = "blockheaders"
# typed pre-disconnect diagnostic (p2p.proto RejectMessage)
MSG_REJECT = "reject"

# Protocol-version tiers (flows/src/{v7,v8,v10}/mod.rs + flow_context.rs:63):
# v7 = base flow set, v8/v9 = + block-body requests (body-only IBD),
# v10 = + pruning-point SMT state (Toccata).  The handshake negotiates
# min(local, peer) and flows outside the negotiated tier are refused.
PROTOCOL_VERSION = 10
MIN_PROTOCOL_VERSION = 7
_MSG_MIN_VERSION = {
    MSG_REQUEST_BLOCK_BODIES: 8,
    MSG_BLOCK_BODIES: 8,
    MSG_REQUEST_HEADERS: 8,  # headers-first rides the body-only tier
    MSG_HEADERS: 8,
    MSG_REQUEST_PP_SMT: 10,
    MSG_PP_SMT_CHUNK: 10,
}
# one day before Toccata activation upgraded nodes stop accepting outdated
# peers (flow_context.rs:827-838)
_ACTIVATION_GATE_SECONDS = 24 * 60 * 60

# peer misbehavior accounting (flows ProtocolError + the reference's
# ban-score ladder): repeat offenses accumulate per connection; crossing
# the threshold bans the peer's IP in the address manager, which both
# refuses future inbound accepts and stops outbound redials
PEER_BAN_SCORE = int(os.environ.get("KASPA_TPU_BAN_SCORE", "100"))
# tx-relay hygiene ladder: sustained hostility crosses the ban threshold,
# honest noise (the odd orphan, a lost RBF race) never does
TX_ORPHAN_POINTS = 2  # orphan storm: ban after ~50 parentless relays
TX_DOUBLE_SPEND_POINTS = 5  # double-spend/RBF-churn chains: ban after ~20
TX_INVALID_POINTS = 30  # invalid signature/script: outright hostile
# an INV larger than this is a flood, not gossip (the reference bounds
# inv batching at MAX_INV_PER_TX_INV_MSG)
MAX_INV_PER_MSG = 512
# a requested txid the peer never delivered stops shadowing re-requests
# after this long
TX_REQUEST_TTL_SECONDS = 30.0
# an IBD donor that stops making progress (no message advancing the sync
# for this long) is abandoned — the one-active-sync slot must not be
# wedgeable by a stalled or malicious peer
IBD_DEADLINE_SECONDS = float(os.environ.get("KASPA_TPU_IBD_DEADLINE", "120"))

_MISBEHAVIOR_POINTS = REGISTRY.counter_family(
    "p2p_misbehavior_points", "reason", help="misbehavior points assessed, by offense"
)
_PEERS_BANNED = REGISTRY.counter("p2p_peers_banned", help="peers that crossed the ban-score threshold")
_IBD_TIMEOUTS = REGISTRY.counter("p2p_ibd_timeouts", help="in-flight syncs abandoned for lack of progress")
from kaspa_tpu.observability.shed import SHED as _SHED  # noqa: E402  (family declared once there)

# serve-side SMT snapshot lifetime (prune_caches): a snapshot nobody has
# requested for the TTL is dead weight (it holds the full lane/segment
# export); one whose anchor the local pruning point has moved past gets a
# shorter grace so a receiver mid-page (which refreshes last-use every
# chunk request) can finish, but an abandoned transfer cannot pin it
SMT_SNAPSHOT_TTL_SECONDS = 300.0
SMT_SNAPSHOT_STALE_GRACE_SECONDS = 60.0


def _activation_gate_blocks(target_time_per_block_ms: int) -> int:
    """DAA-score horizon equal to one day of blocks.  Division before
    rounding: the old per-second blocks-rate factor collapsed to 1 for any
    target slower than 1 BPS (round(1000/10000) == 0 → clamped to 1), which
    turned the one-day gate into ten days on sub-1-BPS networks."""
    return round(_ACTIVATION_GATE_SECONDS * 1000 / target_time_per_block_ms)


class ProtocolError(Exception):
    """Peer misbehavior that warrants disconnect/ban (flows ProtocolError).

    ``points`` is the misbehavior score the reader loop assesses before
    dropping the connection.  Handshake outcomes that reflect OUR state or
    a misconfiguration rather than hostility (self-connection via our own
    gossiped address, wrong network, version mismatch, busy sync slot) set
    0 — banning by IP on those would take out every co-hosted node behind
    the same address."""

    def __init__(self, msg: str, points: int = 100):
        super().__init__(msg)
        self.points = points


@dataclass
class Peer:
    """Router endpoint for one connection (p2p/src/core/router.rs)."""

    node: "Node"
    remote: "Peer | None" = None
    handshaken: bool = False
    # negotiated protocol tier: min(our version, peer's advertised
    # version); floored until the handshake so pre-handshake messages
    # from later tiers are refused, not served
    protocol_version: int = MIN_PROTOCOL_VERSION
    inbox: deque = field(default_factory=deque)
    known_blocks: set = field(default_factory=set)
    known_txs: set = field(default_factory=set)
    # the remote node's identity nonce (learned from its version message);
    # link-level fault planes key partitions on (our id, remote_id)
    remote_id: int | None = None

    def send(self, msg_type: str, payload) -> None:
        """Enqueue on the remote peer's inbox and drain it (sync transport)."""
        self.remote.inbox.append((msg_type, payload))
        self.remote.node._drain(self.remote)


class Node:
    """A full node instance: consensus + mempool + flow handlers + hub."""

    def __init__(
        self,
        consensus: Consensus,
        name: str = "node",
        mempool_seed: int | None = None,
        template_debounce: float = 0.0,
        ident: int | None = None,
    ):
        import threading

        from kaspa_tpu.consensus.manager import ConsensusManager
        from kaspa_tpu.pipeline import ConsensusPipeline

        from kaspa_tpu.ingest import IngestTier

        self.name = name
        self.cmgr = ConsensusManager(consensus)
        # deterministic template-selection sampling: the same seed makes
        # frontier weighted sampling (and thus SUSTAIN fingerprints)
        # byte-reproducible across runs and across the consensus swaps below
        self.mempool_seed = mempool_seed
        # tx-churn template rebuilds collapse to one per debounce window
        # (0 = rebuild on next request, the historical behavior)
        self.template_debounce = template_debounce
        self.mining = MiningManager(consensus, seed=mempool_seed, template_debounce=template_debounce)
        # requested-but-undelivered txids: txid -> request time.  Shared
        # across peers so N connections advertising the same flood tx cost
        # one request, not N (flowcontext transactions_spread dedup role)
        self._tx_requested: dict[bytes, float] = {}
        # requested-but-undelivered relay blocks: in a mesh of N peers the
        # same INV arrives from every neighbor while the first copy is
        # still in flight or mid-validation; without this ledger each
        # arrival re-requests the block and one INV burst amplifies into
        # O(peers) block transfers per node (the swarm drill's
        # relay-amplification budget measures exactly this)
        self._block_requested: dict[bytes, float] = {}
        # wired by the daemon; None in bare in-process tests (flows no-op)
        self.address_manager = None
        self.listen_port = 0  # advertised in the version handshake
        import secrets

        # per-node identity nonce (the reference's version message peer id):
        # a version carrying OUR id is a self-connection and is dropped.
        # ``ident`` pins it (swarm drills: link-level partitions key on it
        # and the event log must be byte-reproducible); default stays random
        self.id = secrets.randbits(64) if ident is None else int(ident)
        # advertised protocol tier; tests cap this to simulate old peers
        self.protocol_version = PROTOCOL_VERSION
        self.cmgr.on_swap(self._on_consensus_swap)
        self.peers: list = []  # the Hub (p2p/src/core/hub.rs)
        self.orphan_blocks: dict[bytes, Block] = {}  # flowcontext/orphans.rs
        self._ibd: dict = {}  # proof-IBD state machine (one active sync)
        # single-writer discipline: wire reader threads and RPC dispatch all
        # serialize consensus/mempool access through this lock.  Ranked
        # BELOW the pipeline's consensus-commit lock (rank 10): handlers
        # take node -> commit, never the inverse (LockCtx asserts this
        # under KASPA_TPU_LOCK_DEBUG)
        self.lock = LockCtx("node", rank=5)
        # the concurrent pipeline IS the block intake — relay, RPC submit and
        # IBD all flow through it (the reference runs its 4-processor
        # pipeline always, consensus/src/consensus/mod.rs:369-401; there is
        # no synchronous alternative path)
        self.pipeline = ConsensusPipeline(consensus, workers=2)
        # batched admission front door (kaspa_tpu/ingest/): RPC submits and
        # P2P relay enqueue tickets; whoever pumps under the node lock
        # admits every concurrently-queued entrant in one wave with a single
        # coalesced verify dispatch (the standalone_tx traffic class)
        self.ingest = IngestTier(self.mining, lock=self.lock)
        # INV-relay damping (resilience/overload.py brownout seam): while
        # set, outbound tx INVs are suppressed — peers re-learn the pool
        # from post-recovery gossip; block relay is never damped
        self.relay_damping = False

    def set_relay_damping(self, active: bool) -> None:
        self.relay_damping = bool(active)

    @property
    def consensus(self) -> Consensus:
        return self.cmgr.consensus

    def _on_consensus_swap(self, new_consensus) -> None:
        """Staging commit: rebuild the mempool facade on the new consensus
        (pending txs are dropped — they reference the stale DAG)."""
        from kaspa_tpu.pipeline import ConsensusPipeline

        self.mining = MiningManager(
            new_consensus, seed=self.mempool_seed, template_debounce=self.template_debounce
        )
        self.ingest.mining = self.mining  # queued entrants admit against the new DAG
        self._drop_ibd_pipeline()
        old = self.pipeline
        self.pipeline = ConsensusPipeline(new_consensus, workers=2)
        old.shutdown()

    def _drop_ibd_pipeline(self) -> None:
        cached = getattr(self, "_ibd_pipeline", None)
        if cached is not None:
            self._ibd_pipeline = None
            cached[1].shutdown()

    def shutdown(self) -> None:
        """Tear down one node instance cleanly: close every peer link and
        stop the worker pools.  Multi-instance hosts (swarm drills spin up
        N nodes in one process) call this per node so the fleet's threads
        and sockets don't outlive the run."""
        for peer in list(self.peers):
            if hasattr(peer, "close"):
                try:
                    peer.close()
                except Exception:
                    pass
        self.peers.clear()
        self._drop_ibd_pipeline()
        self.pipeline.shutdown()

    def prune_caches(self, now: float | None = None) -> None:
        """Drop serve-side IBD snapshots that outlived their usefulness.

        Called under ``self.lock`` (SMT request handler + the daemon's
        metrics tick).  The SMT snapshot ``(anchor_pp, state, last_use)``
        dies when idle past SMT_SNAPSHOT_TTL_SECONDS, or — once the local
        pruning point has advanced past its anchor — after the shorter
        stale grace (an active receiver refreshes last_use every chunk
        request and finishes; an abandoned transfer cannot pin the export
        forever).  The UTXO snapshot is keyed to the live pruning point
        only, so it drops as soon as the anchor moves.
        """
        now = _monotonic() if now is None else now
        if self._ibd:
            # IBD progress deadline: _handle refreshes last_progress on
            # every message from the donor; a donor that goes quiet past
            # the deadline loses the (single) sync slot and the connection
            last = self._ibd.setdefault("last_progress", now)
            if now - last > IBD_DEADLINE_SECONDS:
                stalled, self._ibd = self._ibd, {}
                _IBD_TIMEOUTS.inc()
                staging = stalled.get("staging")
                if staging is not None:
                    staging.cancel()
                self._drop_ibd_pipeline()
                donor = stalled.get("peer")
                self.score_misbehavior(donor, "ibd_stall", 40)
                if donor is not None and hasattr(donor, "close"):
                    donor.close()
        pp = self.consensus.pruning_processor.pruning_point
        snap = getattr(self, "_pp_smt_snapshot", None)
        if snap is not None:
            # tests prime bare (pp, state) snapshots; treat those as fresh
            anchor, last_use = snap[0], (snap[2] if len(snap) > 2 else now)
            limit = SMT_SNAPSHOT_TTL_SECONDS if anchor == pp else SMT_SNAPSHOT_STALE_GRACE_SECONDS
            if now - last_use > limit:
                self._pp_smt_snapshot = None
        usnap = getattr(self, "_pp_utxo_snapshot", None)
        if usnap is not None and usnap[0] != pp:
            self._pp_utxo_snapshot = None

    def score_misbehavior(self, peer, reason: str, points: int) -> bool:
        """Assess misbehavior points against ``peer``; True once banned.

        Per-connection accumulator with an IP-level consequence: crossing
        PEER_BAN_SCORE bans the address in the address manager (inbound
        accepts refused, outbound dials stopped, gossip filtered).  Callers
        decide whether to also close the connection — the reader loop is
        usually already unwinding it.
        """
        if peer is None:
            return False
        score = getattr(peer, "misbehavior_score", 0) + points
        peer.misbehavior_score = score
        _MISBEHAVIOR_POINTS.inc(reason, points)
        if score < PEER_BAN_SCORE:
            return False
        _PEERS_BANNED.inc()
        addr = getattr(peer, "peer_address", None)
        if self.address_manager is not None and addr is not None:
            self.address_manager.ban(addr.ip)
        return True

    # --- hub / relay (flow_context.rs on_new_block -> broadcast) ---

    def broadcast_block(self, block: Block) -> None:
        # snapshot: a failed send self-removes the peer from self.peers
        for peer in list(self.peers):
            if block.hash not in peer.known_blocks:
                peer.known_blocks.add(block.hash)
                peer.send(MSG_INV_BLOCK, block.hash)

    def broadcast_tx(self, tx) -> None:
        if self.relay_damping:
            if self.peers:
                _SHED.inc("inv_damping")
            return
        for peer in list(self.peers):
            if tx.id() not in peer.known_txs:
                peer.known_txs.add(tx.id())
                peer.send(MSG_INV_TXS, [tx.id()])

    def submit_block(self, block: Block) -> str:
        status = self.pipeline.validate_and_insert_block(block)
        self.mining.handle_new_block_transactions(block.transactions, self.consensus.get_virtual_daa_score())
        self._try_unorphan(block.hash)
        self.broadcast_block(block)
        return status

    def submit_transaction(self, tx) -> list[bytes]:
        """RPC-facing admission through the batched ingest tier.

        Same contract as the old direct call — raises on rejection, parks
        orphans silently, returns RBF-evicted txids — but concurrent
        submitters now share one verify wave, and the relay only carries
        txs that actually entered a pool."""
        from kaspa_tpu.ingest import SOURCE_RPC

        ticket = self.ingest.admit(tx, SOURCE_RPC)
        evicted = ticket.raise_for_status()
        self.broadcast_tx(tx)
        return evicted

    # --- flow handlers (protocol/flows/src/v7/) ---

    def _drain(self, peer: Peer) -> None:
        # re-entrancy guard: a handler that triggers a send back to this
        # peer (chunked IBD ping-pong) must ENQUEUE, not recurse — the
        # outer drain loop picks the message up iteratively
        if getattr(peer, "_draining", False):
            return
        peer._draining = True
        try:
            while peer.inbox:
                msg_type, payload = peer.inbox.popleft()
                self._handle(peer, msg_type, payload)
        finally:
            peer._draining = False

    def _handle(self, peer: Peer, msg_type: str, payload) -> None:
        # any message from the active IBD donor counts as sync progress
        # (the deadline in prune_caches fires on silence, not slowness)
        if self._ibd and self._ibd.get("peer") is peer:
            self._ibd["last_progress"] = _monotonic()
        # tier gate: flows introduced in a later protocol version than the
        # negotiated one are refused (the reference simply never registers
        # them for the old tier, flow_context.rs:837-852)
        min_v = _MSG_MIN_VERSION.get(msg_type)
        if min_v is not None and peer.protocol_version < min_v:
            raise ProtocolError(
                f"message {msg_type} requires protocol v{min_v} but v{peer.protocol_version} was negotiated"
            )
        if msg_type == MSG_VERSION:
            # handshake.rs: version negotiation incl. network match
            if isinstance(payload, dict) and payload.get("network", self.consensus.params.name) != self.consensus.params.name:
                raise ProtocolError(f"network mismatch: {payload.get('network')}", points=0)
            peer_pv = payload.get("protocol_version", MIN_PROTOCOL_VERSION) if isinstance(payload, dict) else MIN_PROTOCOL_VERSION
            if peer_pv < MIN_PROTOCOL_VERSION:
                raise ProtocolError(
                    f"protocol version mismatch: ours {self.protocol_version}, peer {peer_pv}", points=0
                )
            # one day before Toccata activation, refuse pre-Toccata tiers:
            # a v<10 peer cannot serve/receive lane state and would fork
            # (flow_context.rs:827-841)
            params = self.consensus.params
            gate_daa = self.consensus.get_virtual_daa_score() + _activation_gate_blocks(
                params.target_time_per_block
            )
            if params.toccata_active(gate_daa) and peer_pv < 10:
                raise ProtocolError(
                    f"protocol v10 required near Toccata activation (peer advertises v{peer_pv})", points=0
                )
            peer.protocol_version = min(self.protocol_version, peer_pv)
            if isinstance(payload, dict) and payload.get("id"):
                # link identity for the partition fault plane (swarm drills)
                peer.remote_id = payload["id"]
            if isinstance(payload, dict) and payload.get("id") and payload["id"] == self.id:
                # gossip taught us our own address and we dialed ourselves;
                # scrub the LISTEN address (what gossip stored), not the
                # dialing socket's ephemeral source address
                if self.address_manager is not None and getattr(peer, "peer_address", None):
                    from kaspa_tpu.p2p.address_manager import NetAddress

                    self.address_manager.remove(peer.peer_address)
                    if payload.get("listen_port"):
                        self.address_manager.remove(
                            NetAddress(peer.peer_address.ip, payload["listen_port"])
                        )
                if hasattr(peer, "close"):
                    peer.close()
                raise ProtocolError("self-connection detected (matching version id)", points=0)
            # record the peer's advertised listen address for gossip
            # (flow_context.rs registers it with the address manager)
            if (
                isinstance(payload, dict)
                and payload.get("listen_port")
                and getattr(peer, "peer_address", None) is not None
            ):
                from kaspa_tpu.p2p.address_manager import NetAddress

                # remember the peer's listen identity on the peer itself so
                # the connection manager never back-dials a live inbound peer
                peer.advertised_address = NetAddress(peer.peer_address.ip, payload["listen_port"])
                if self.address_manager is not None:
                    self.address_manager.add_address(peer.advertised_address)
            if not getattr(peer, "version_sent", True):
                # inbound wire peer: reciprocate with our own version
                peer.version_sent = True
                peer.send(
                    MSG_VERSION,
                    {
                        "protocol_version": self.protocol_version,
                        "network": self.consensus.params.name,
                        "listen_port": self.listen_port,
                        "id": self.id,
                    },
                )
            peer.send(MSG_VERACK, self.protocol_version)
        elif msg_type == MSG_VERACK:
            peer.handshaken = True
            if self.address_manager is not None:
                peer.send(MSG_REQUEST_ADDRESSES, {})
        elif msg_type == MSG_REQUEST_ADDRESSES:
            peers = []
            if self.address_manager is not None:
                import itertools

                peers = [
                    str(a)
                    for a in itertools.islice(
                        self.address_manager.iterate_prioritized_random_addresses(), 256
                    )
                ]
            peer.send(MSG_ADDRESSES, peers)
        elif msg_type == MSG_ADDRESSES:
            # gossip intake: feed the address manager (ban-filtered there)
            if self.address_manager is not None:
                from kaspa_tpu.p2p.address_manager import NetAddress

                for a in payload[:256]:
                    try:
                        self.address_manager.add_address(NetAddress.parse(a))
                    except ValueError:
                        continue
        elif msg_type == "ping":
            peer.send("pong", payload)
        elif msg_type == "pong":
            pass
        elif msg_type == MSG_INV_BLOCK:
            # blockrelay/flow.rs: request unknown relay blocks — but only
            # once per block fleet-wide: in a mesh every neighbor relays
            # the same INV while the first copy is still in flight, and
            # re-requesting from each would amplify one burst into
            # O(peers) transfers per node (see _block_requested)
            now = _monotonic()
            if self._block_requested:
                self._block_requested = {
                    h: ts for h, ts in self._block_requested.items()
                    if now - ts < TX_REQUEST_TTL_SECONDS
                }
            if (
                not self.consensus.storage.statuses.is_valid(payload)
                and payload not in self.orphan_blocks
                and payload not in self._block_requested
                and not self.pipeline.deps.is_pending(payload)
            ):
                self._block_requested[payload] = now
                peer.send(MSG_REQUEST_BLOCK, [payload])
        elif msg_type == MSG_REQUEST_BLOCK:
            for h in payload:
                if self.consensus.storage.block_transactions.has(h):
                    header = self.consensus.storage.headers.get(h)
                    txs = self.consensus.storage.block_transactions.get(h)
                    peer.send(MSG_BLOCK, Block(header, txs))
        elif msg_type == MSG_BLOCK:
            self._on_relay_block(peer, payload)
        elif msg_type == MSG_INV_TXS:
            if len(payload) > MAX_INV_PER_MSG:
                # inventory flood: refuse the oversized frame, charge the
                # sender, and don't fan N*request traffic out of it
                self.score_misbehavior(peer, "inv_flood", 20)
                return
            now = _monotonic()
            # expire requests a peer never answered so re-advertisement works
            if self._tx_requested:
                self._tx_requested = {
                    t: ts for t, ts in self._tx_requested.items()
                    if now - ts < TX_REQUEST_TTL_SECONDS
                }
            mempool = self.mining.mempool
            unknown = [
                t
                for t in payload
                if not mempool.has(t) and t not in mempool.accepted and t not in self._tx_requested
            ]
            if unknown:
                for t in unknown:
                    self._tx_requested[t] = now
                peer.send(MSG_REQUEST_TXS, unknown)
        elif msg_type == MSG_REQUEST_TXS:
            for txid in payload[:MAX_INV_PER_MSG]:
                entry = self.mining.mempool.get(txid)
                if entry is not None:
                    peer.known_txs.add(txid)
                    peer.send(MSG_TX, entry.tx)
        elif msg_type == MSG_TX:
            self._on_relay_tx(peer, payload)
        elif msg_type == MSG_IBD_BLOCK_LOCATOR:
            # negotiate.rs donor side: highest locator entry we know anchors
            # the antipast query; unknown locator => serve from our pruning
            # point (the syncer should have proof-synced first)
            reach = self.consensus.reachability
            sink = self.consensus.sink()
            # only a chain ancestor of our sink anchors the walk safely:
            # retained anticone blocks near the retention boundary may have
            # had their selected-parent chain pruned underneath them
            common = next(
                (h for h in payload if reach.has(h) and reach.is_chain_ancestor_of(h, sink)),
                None,
            )
            if common is None:
                common = self.consensus.pruning_processor.pruning_point
            self._serve_antipast_chunk(peer, common)
        elif msg_type == MSG_REQUEST_ANTIPAST:
            # continuation request: low is the highest chain block the
            # previous chunk reached (flow.rs IBD batching).  Re-apply the
            # same pruning-safe anchoring as the locator path, and ALWAYS
            # reply — a silently dropped continuation would wedge the
            # syncer's _ibd state forever
            reach = self.consensus.reachability
            sink = self.consensus.sink()
            low = payload
            if not (reach.has(low) and reach.is_chain_ancestor_of(low, sink)):
                low = self.consensus.pruning_processor.pruning_point
            self._serve_antipast_chunk(peer, low)
        elif msg_type == MSG_IBD_BLOCKS:
            staging = self._ibd.get("staging") if self._ibd.get("peer") is peer else None
            target = staging.consensus if staging is not None else self.consensus
            self._insert_ibd_batch(target, payload["blocks"])
            if not payload["done"]:
                # bounded chunks: pull the next batch from where we stopped
                peer.send(MSG_REQUEST_ANTIPAST, payload["continuation"])
            elif staging is not None:
                self._finalize_proof_ibd(staging)
        elif msg_type == MSG_REQUEST_IBD_CHAIN_INFO:
            sink = self.consensus.sink()
            peer.send(
                MSG_IBD_CHAIN_INFO,
                {
                    "sink": sink,
                    "sink_blue_work": self.consensus.storage.ghostdag.get_blue_work(sink),
                    "pruning_point": self.consensus.pruning_processor.pruning_point,
                },
            )
        elif msg_type == MSG_IBD_CHAIN_INFO:
            self._on_chain_info(peer, payload)
        elif msg_type == MSG_REQUEST_PRUNING_PROOF:
            peer.send(MSG_PRUNING_PROOF, self.consensus.pruning_proof_manager.build_proof())
        elif msg_type == MSG_PRUNING_PROOF:
            if self._ibd.get("peer") is peer and self._ibd.get("phase") == "proof":
                # early tier gate: the proof's claimed PP header reveals a
                # post-Toccata bootstrap before the (much larger) trusted
                # data + UTXO set are transferred; the authoritative check
                # after proof validation remains in _on_pp_utxo_chunk
                if payload and payload[0] and peer.protocol_version < 10:
                    claimed_pp = payload[0][-1]
                    if self.consensus.params.toccata_active(claimed_pp.daa_score):
                        self._ibd = {}
                        raise ProtocolError(
                            "peer protocol tier too old for a post-Toccata bootstrap (needs v10)"
                        )
                self._ibd["proof"] = payload
                self._ibd["phase"] = "trusted"
                peer.send(MSG_REQUEST_TRUSTED_DATA, {})
        elif msg_type == MSG_REQUEST_TRUSTED_DATA:
            peer.send(MSG_TRUSTED_DATA, self.consensus.pruning_proof_manager.get_trusted_data())
        elif msg_type == MSG_TRUSTED_DATA:
            if self._ibd.get("peer") is peer and self._ibd.get("phase") == "trusted":
                self._ibd["trusted"] = payload
                self._ibd["phase"] = "utxos"
                self._ibd["utxo"] = {}
                peer.send(MSG_REQUEST_PP_UTXOS, 0)
        elif msg_type == MSG_REQUEST_PP_UTXOS:
            # snapshot the sorted item list once per pruning point — chunk
            # requests must not re-sort the whole set under the node lock
            pp = self.consensus.pruning_processor.pruning_point
            cached = getattr(self, "_pp_utxo_snapshot", None)
            if cached is None or cached[0] != pp:
                items = sorted(
                    self.consensus.pruning_processor.pruning_utxo_set.items(),
                    key=lambda kv: (kv[0].transaction_id, kv[0].index),
                )
                self._pp_utxo_snapshot = cached = (pp, items)
            items = cached[1]
            start = int(payload)
            chunk = items[start : start + PP_UTXO_CHUNK_SIZE]
            peer.send(
                MSG_PP_UTXO_CHUNK,
                {"offset": start, "pairs": chunk, "done": start + len(chunk) >= len(items)},
            )
        elif msg_type == MSG_PP_UTXO_CHUNK:
            self._on_pp_utxo_chunk(peer, payload)
        elif msg_type == MSG_REQUEST_PP_SMT:
            # the request pins the pruning point (RequestPruningPointSmtState
            # carries pruning_point_hash in the reference, ibd/flow.rs:714):
            # a mid-IBD local pruning advance must not switch snapshots under
            # a receiver still paging the old state
            req_pp = payload["pp"]
            self.prune_caches()  # expired snapshots never serve another chunk
            cached = getattr(self, "_pp_smt_snapshot", None)
            if cached is None or cached[0] != req_pp:
                if req_pp != self.consensus.pruning_processor.pruning_point:
                    # neither the cached snapshot nor our live PP: cannot serve
                    peer.send(
                        MSG_PP_SMT_CHUNK,
                        {"active": False, "meta": None, "offset": 0, "lanes": [], "segment": [], "done": True},
                    )
                    return
                cached = (req_pp, self.consensus.export_pp_lane_state(), _monotonic())
            else:
                cached = (cached[0], cached[1], _monotonic())  # refresh last-use
            self._pp_smt_snapshot = cached
            state = cached[1]
            if state is None:
                peer.send(
                    MSG_PP_SMT_CHUNK,
                    {"active": False, "meta": None, "offset": 0, "lanes": [], "segment": [], "done": True},
                )
            else:
                meta, lanes, segment = state
                start = int(payload["offset"])
                lane_part = lanes[start : start + PP_SMT_CHUNK_SIZE]
                rem = PP_SMT_CHUNK_SIZE - len(lane_part)
                seg_start = max(0, start - len(lanes))
                seg_part = segment[seg_start : seg_start + rem] if rem > 0 else []
                total = len(lanes) + len(segment)
                sent = start + len(lane_part) + len(seg_part)
                peer.send(
                    MSG_PP_SMT_CHUNK,
                    {
                        "active": True,
                        "meta": meta if start == 0 else None,
                        "offset": start,
                        "lanes": lane_part,
                        "segment": seg_part,
                        "done": sent >= total,
                    },
                )
        elif msg_type == MSG_PP_SMT_CHUNK:
            self._on_pp_smt_chunk(peer, payload)
        elif msg_type == MSG_REQUEST_HEADERS:
            # serve one bounded chunk of headers above `low` along the
            # antipast walk (request_headers.rs).  A known off-chain anchor
            # is fine: antipast_hashes_between resolves it to the common
            # chain block; only an UNKNOWN anchor falls back pruning-safe
            low = payload
            if not self.consensus.reachability.has(low):
                low = self.consensus.pruning_processor.pruning_point
            self._serve_antipast_chunk(peer, low, headers_only=True)
        elif msg_type == MSG_HEADERS:
            if not getattr(peer, "_headers_first", False):
                return  # unsolicited headers stream
            statuses = self.consensus.storage.statuses
            bodies = self.consensus.storage.block_transactions
            need_bodies = []
            for h in payload["headers"]:
                h.invalidate_cache()  # wire-decoded cache is untrusted
                status = statuses.get(h.hash)
                if status is None:
                    try:
                        self.consensus.validate_and_insert_header(h)
                    except RuleError:
                        continue
                    status = statuses.get(h.hash)
                # fetch bodies only for header-only blocks we lack — never
                # for already-complete or known-invalid ones
                if status == StatusesStore.STATUS_HEADER_ONLY and not bodies.has(h.hash):
                    need_bodies.append(h.hash)
            for i in range(0, len(need_bodies), IBD_BATCH_SIZE):
                self.request_bodies(peer, need_bodies[i : i + IBD_BATCH_SIZE])
            if not payload["done"]:
                peer.send(MSG_REQUEST_HEADERS, payload["continuation"])
            else:
                peer._headers_first = False
        elif msg_type == MSG_REJECT:
            # peer-reported protocol rejection: log and let the connection
            # wind down (p2p.proto RejectMessage semantics)
            from kaspa_tpu.core.log import get_logger

            get_logger("p2p").warn("peer rejected us: %s", payload)
            if hasattr(peer, "close"):
                peer.close()
        elif msg_type == MSG_REQUEST_BLOCK_BODIES:
            # v8 body-only serving (request_block_bodies.rs): bodies for
            # blocks the requester holds headers for
            out = []
            # bounded like the chunked IBD path: a peer cannot make the
            # server materialize its whole body store in one frame
            for h in payload[:IBD_BATCH_SIZE]:
                if self.consensus.storage.block_transactions.has(h):
                    out.append((h, self.consensus.storage.block_transactions.get(h)))
            peer.send(MSG_BLOCK_BODIES, out)
        elif msg_type == MSG_BLOCK_BODIES:
            # attach received bodies to header-only blocks and run them
            # through the normal intake pipeline
            blocks = []
            for h, txs in payload:
                if not self.consensus.storage.headers.has(h):
                    continue
                if self.consensus.storage.block_transactions.has(h):
                    continue  # already have the body
                blocks.append(Block(self.consensus.storage.headers.get(h), list(txs)))
            if blocks:
                self._insert_ibd_batch(self.consensus, blocks)

    def _insert_ibd_batch(self, target: Consensus, blocks) -> None:
        """Bulk intake through the concurrent pipeline: the whole batch goes
        in flight at once (children park on pending parents in the deps
        manager), stage workers overlap hashing/device dispatch, and the
        virtual worker drains multiple blocks per resolution — the IBD
        analog of the reference's pipelined block processing
        (flows/src/ibd/flow.rs feeding consensus's pipeline).  The wire
        reader holds the node lock throughout, so no RPC reader observes
        intermediate virtual state.  One pipeline is kept per sync target
        (not per message) so a chunked IBD doesn't churn threads."""
        from kaspa_tpu.pipeline import ConsensusPipeline

        if target is self.consensus:
            pipe = self.pipeline  # plain IBD rides the steady-state pipeline
        else:
            cached = getattr(self, "_ibd_pipeline", None)
            if cached is None or cached[0] is not target:
                if cached is not None:
                    cached[1].shutdown()
                cached = (target, ConsensusPipeline(target, workers=2))
                self._ibd_pipeline = cached
            pipe = cached[1]
        futures = [pipe.submit(b) for b in blocks]
        for f in futures:
            try:
                f.result(timeout=600)
            except RuleError:
                pass  # invalid blocks within an IBD batch are skipped

    def _on_relay_tx(self, peer: Peer, tx) -> None:
        """Tx-relay intake with flood hygiene (flows/src/v7/txrelay/flow.rs).

        Admission rides the batched ingest tier (source ``p2p``).  The
        verdict feeds the misbehavior ladder: parentless relays (orphan
        storms) and double-spend/RBF-churn chains accumulate points until
        the peer crosses the ban score; invalid signatures/scripts are
        charged hard.  Honest outcomes — duplicates from gossip races, a
        fee floor, our own backpressure — are free.  Only txs that entered
        the live pool are rebroadcast (orphans would propagate the storm).
        """
        from kaspa_tpu.consensus.processes.transaction_validator import TxRuleError
        from kaspa_tpu.ingest import SOURCE_P2P

        txid = tx.id()
        peer.known_txs.add(txid)
        self._tx_requested.pop(txid, None)
        ticket = self.ingest.admit(tx, SOURCE_P2P)
        if ticket.status == "accepted":
            self.broadcast_tx(tx)
            return
        banned = False
        if ticket.status == "orphaned":
            banned = self.score_misbehavior(peer, "tx_orphan", TX_ORPHAN_POINTS)
        elif isinstance(ticket.error, TxRuleError):
            banned = self.score_misbehavior(peer, "invalid_tx", TX_INVALID_POINTS)
        elif isinstance(ticket.error, MempoolError) and ticket.error.code in (
            "tx-double-spend",
            "tx-rbf-rejected",
        ):
            banned = self.score_misbehavior(peer, "tx_double_spend", TX_DOUBLE_SPEND_POINTS)
        # everything else — including code "node-overloaded" (OUR brownout
        # shed the relay, the peer did nothing wrong) — stays unscored
        # alongside duplicates, fee floors and ingest backpressure
        if banned and hasattr(peer, "close"):
            peer.close()

    def _on_relay_block(self, peer: Peer, block: Block) -> None:
        # flight trace starts at the wire: the pipeline's own begin() on
        # submit is idempotent and re-joins this root, so the recorded
        # block time includes the p2p intake hop
        ctx = flight.begin(block.hash) if flight.enabled() else None
        with trace.span("p2p.block_receive", parent=ctx):
            self._block_requested.pop(block.hash, None)  # delivered: allow re-request if invalid
            peer.known_blocks.add(block.hash)  # sender has it: don't echo the inv back
            parents = block.header.direct_parents()
            # a parent already in flight inside the pipeline counts as present:
            # the deps manager parks the child until the parent commits (the
            # reference's out-of-order intake, deps_manager.rs) — only parents
            # neither stored nor in flight make this an orphan
            missing = [
                p
                for p in parents
                if not self.consensus.storage.headers.has(p) and not self.pipeline.deps.is_pending(p)
            ]
            if missing:
                # orphan: request missing ancestors (orphan resolution, flow.rs)
                self.orphan_blocks[block.hash] = block
                peer.send(MSG_REQUEST_BLOCK, missing)
        if missing:
            return
        try:
            self.pipeline.validate_and_insert_block(block)
        except RuleError:
            # invalid relay blocks are an offense, not an instant ban: an
            # honest peer can relay a block it hasn't fully validated, but
            # a stream of them crosses the threshold
            if self.score_misbehavior(peer, "invalid_block", 40) and hasattr(peer, "close"):
                peer.close()
            return
        self.mining.handle_new_block_transactions(block.transactions, self.consensus.get_virtual_daa_score())
        self._try_unorphan(block.hash)
        self.broadcast_block(block)

    def _try_unorphan(self, new_hash: bytes) -> None:
        """revalidate_orphans: process orphans whose parents arrived.

        Each round submits EVERY ready orphan to the pipeline at once —
        siblings overlap their header/body stages — then collects results."""
        progress = True
        while progress:
            progress = False
            ready = [
                (h, block)
                for h, block in list(self.orphan_blocks.items())
                if all(self.consensus.storage.headers.has(p) for p in block.header.direct_parents())
            ]
            futures = []
            for h, block in ready:
                del self.orphan_blocks[h]
                futures.append((block, self.pipeline.submit(block)))
            for block, fut in futures:
                try:
                    fut.result()
                    self.broadcast_block(block)
                    progress = True
                except RuleError:
                    pass

    def _serve_antipast_chunk(self, peer: Peer, low: bytes, headers_only: bool = False) -> None:
        """One bounded IBD batch above ``low`` plus the continuation point
        (flow.rs streams IBD_BATCH_SIZE chunks; the syncer requests the
        next batch from ``continuation``).  ``headers_only`` serves the v8
        headers-first stream over the same walk/batching discipline."""
        from kaspa_tpu.consensus.processes.sync import SyncManager

        sm = SyncManager(self.consensus)
        sink = self.consensus.sink()
        hashes, highest = sm.antipast_hashes_between(low, sink, max_blocks=IBD_BATCH_SIZE)
        bts = self.consensus.storage.block_transactions
        hdrs = self.consensus.storage.headers
        done = highest == sink or not hashes
        if headers_only:
            headers = [hdrs.get(h) for h in hashes if hdrs.has(h)]
            peer.send(MSG_HEADERS, {"headers": headers, "done": done, "continuation": highest})
            return
        blocks = [Block(hdrs.get(h), bts.get(h)) for h in hashes if bts.has(h)]
        peer.send(
            MSG_IBD_BLOCKS,
            {"blocks": blocks, "done": done, "continuation": highest},
        )

    def _send_locator(self, peer: Peer, consensus: Consensus) -> None:
        from kaspa_tpu.consensus.processes.sync import SyncManager

        sm = SyncManager(consensus)
        locator = sm.create_block_locator_from_pruning_point(
            consensus.sink(), consensus.pruning_processor.pruning_point
        )
        peer.send(MSG_IBD_BLOCK_LOCATOR, locator)

    def ibd_from(self, peer: Peer) -> None:
        """IBD negotiation (ibd/flow.rs determine_ibd_type): ask for the
        peer's chain info, then either relay-style catch-up (peer's pruning
        point known locally) or a pruning-proof sync into a staging
        consensus."""
        peer.send(MSG_REQUEST_IBD_CHAIN_INFO, {})

    def _on_chain_info(self, peer: Peer, info: dict) -> None:
        peer_pp = info["pruning_point"]
        sink = self.consensus.sink()
        our_work = self.consensus.storage.ghostdag.get_blue_work(sink)
        if info["sink_blue_work"] <= our_work:
            return  # nothing to gain from this peer
        if self._ibd:
            return  # one sync at a time; don't abandon an in-flight staging
        if (
            self.consensus.reachability.has(peer_pp)
            and (
                self.consensus.reachability.is_dag_ancestor_of(
                    self.consensus.pruning_processor.pruning_point, peer_pp
                )
                or peer_pp == self.consensus.pruning_processor.pruning_point
            )
        ):
            # peer's pruning point is connected within our known history
            # (header-only proof remnants without reachability do NOT count):
            # negotiate with an exponential block locator instead of a full
            # inventory (sync/mod.rs create_block_locator_from_pruning_point)
            self._send_locator(peer, self.consensus)
            return
        # too far behind: headers-proof sync (ibd/flow.rs IbdType::DownloadHeadersProof)
        self._ibd = {"peer": peer, "phase": "proof"}
        peer.send(MSG_REQUEST_PRUNING_PROOF, {})

    def request_bodies(self, peer: Peer, hashes: list[bytes]) -> None:
        """v8 body-only fetch for blocks we hold headers for
        (request_block_bodies.rs client side; requires tier >= 8)."""
        if peer.protocol_version < 8:
            raise ProtocolError("peer protocol tier does not support body requests (needs v8)")
        peer.send(MSG_REQUEST_BLOCK_BODIES, hashes)

    def headers_first_sync(self, peer: Peer) -> None:
        """v8 headers-first catch-up: stream headers above our sink anchor,
        then fetch just the bodies (ibd body_only_ibd_permitted mode)."""
        if peer.protocol_version < 8:
            raise ProtocolError("peer protocol tier does not support headers-first sync (needs v8)")
        if self._ibd:
            # one sync at a time: never race an in-flight (possibly staging)
            # IBD with a second header stream into the same consensus
            raise ProtocolError("a sync is already in flight", points=0)
        peer._headers_first = True
        peer.send(MSG_REQUEST_HEADERS, self.consensus.sink())

    def _on_pp_utxo_chunk(self, peer: Peer, payload: dict) -> None:
        from kaspa_tpu.consensus.processes.pruning_proof import ProofError
        from kaspa_tpu.consensus.utxo import UtxoCollection

        if self._ibd.get("peer") is not peer or self._ibd.get("phase") != "utxos":
            return
        for op, entry in payload["pairs"]:
            self._ibd["utxo"][op] = entry
        if not payload["done"]:
            if not payload["pairs"]:
                self._ibd = {}
                raise ProtocolError("peer sent an empty non-final UTXO chunk (no progress)")
            peer.send(MSG_REQUEST_PP_UTXOS, payload["offset"] + len(payload["pairs"]))
            return
        # all trust material in hand: bootstrap a staging consensus and sync
        # the post-pruning-point history into it; the swap happens only when
        # the staging chain actually carries more blue work than the active
        # one (staging_consensus.rs commit discipline)
        staging = self.cmgr.new_staging()
        try:
            active_ppm = self.consensus.pruning_proof_manager
            staging.consensus.pruning_proof_manager.import_pruning_data(
                self._ibd["proof"],
                self._ibd["trusted"],
                UtxoCollection(self._ibd["utxo"]),
                defender_proof=active_ppm.build_proof(),
            )
        except ProofError as e:
            self._ibd = {}
            staging.cancel()
            raise ProtocolError(f"invalid pruning proof data from peer: {e}") from e
        # KIP-21: a post-Toccata pruning point needs its lane state before
        # any post-PP chain block can be seq-commit-verified
        # (flows/src/ibd/flow.rs:145-150); pre-Toccata starts empty
        sc = staging.consensus
        pp = sc.pruning_processor.pruning_point
        pp_hdr = sc.storage.headers.get(pp)
        if sc.params.toccata_active(pp_hdr.daa_score) and pp != sc.params.genesis.hash:
            if peer.protocol_version < 10:
                # the donor cannot speak the SMT flow: a post-Toccata
                # bootstrap from it would start without lane state and fork
                self._ibd = {}
                staging.cancel()
                raise ProtocolError(
                    "peer protocol tier too old for a post-Toccata bootstrap (needs v10)"
                )
            self._ibd = {
                "peer": peer, "phase": "smt", "staging": staging, "smt_pp": pp,
                "smt_meta": None, "smt_lanes": [], "smt_seg": [],
            }
            peer.send(MSG_REQUEST_PP_SMT, {"pp": pp, "offset": 0})
            return
        self._ibd = {"peer": peer, "phase": "blocks", "staging": staging}
        self._send_locator(peer, staging.consensus)

    def _on_pp_smt_chunk(self, peer: Peer, payload: dict) -> None:
        from kaspa_tpu.consensus.smt_processor import LaneStateError

        if self._ibd.get("peer") is not peer or self._ibd.get("phase") != "smt":
            return
        staging = self._ibd["staging"]
        if not payload.get("active", True):
            # we only request lane state for a post-Toccata PP, so a donor
            # claiming there is none cannot seed a verifiable bootstrap
            self._ibd = {}
            staging.cancel()
            raise ProtocolError("peer cannot serve lane state for a post-Toccata pruning point")
        if payload.get("meta") is not None:
            self._ibd["smt_meta"] = payload["meta"]
        self._ibd["smt_lanes"].extend(payload["lanes"])
        self._ibd["smt_seg"].extend(payload["segment"])
        if not payload["done"]:
            if not payload["lanes"] and not payload["segment"]:
                self._ibd = {}
                staging.cancel()
                raise ProtocolError("peer sent an empty non-final SMT chunk (no progress)")
            peer.send(
                MSG_REQUEST_PP_SMT,
                {
                    "pp": self._ibd["smt_pp"],
                    "offset": payload["offset"] + len(payload["lanes"]) + len(payload["segment"]),
                },
            )
            return
        try:
            staging.consensus.import_pp_lane_state(
                self._ibd["smt_meta"], self._ibd["smt_lanes"], self._ibd["smt_seg"]
            )
        except (LaneStateError, KeyError, TypeError) as e:
            self._ibd = {}
            staging.cancel()
            raise ProtocolError(f"invalid pruning point SMT state from peer: {e}") from e
        self._ibd = {"peer": peer, "phase": "blocks", "staging": staging}
        self._send_locator(peer, staging.consensus)

    def _finalize_proof_ibd(self, staging) -> None:
        self._ibd = {}
        self._drop_ibd_pipeline()
        new_sink = staging.consensus.sink()
        new_work = staging.consensus.storage.ghostdag.get_blue_work(new_sink)
        cur_work = self.consensus.storage.ghostdag.get_blue_work(self.consensus.sink())
        if new_work > cur_work:
            staging.commit()
        else:
            staging.cancel()
            raise ProtocolError("proof-IBD peer failed to deliver the promised chain work")


def connect(a: Node, b: Node) -> tuple[Peer, Peer]:
    """Wire two nodes with a bidirectional in-process connection + handshake."""
    pa = Peer(node=a)  # a's endpoint talking to b
    pb = Peer(node=b)
    pa.remote = pb
    pb.remote = pa
    a.peers.append(pa)
    b.peers.append(pb)
    pa.send(MSG_VERSION, {"protocol_version": a.protocol_version, "network": a.consensus.params.name, "listen_port": 0, "id": a.id})
    pb.send(MSG_VERSION, {"protocol_version": b.protocol_version, "network": b.consensus.params.name, "listen_port": 0, "id": b.id})
    return pa, pb
