"""In-process P2P: routers, flows, and block/tx relay between nodes.

Reference: protocol/p2p (Adaptor/Router/Hub over tonic gRPC, ~60 payload
types) and protocol/flows (one task per flow per peer: handshake, block
relay with orphan resolution, tx relay, IBD).  This round models the flow
layer over an in-process transport — the same peer/message/flow shapes,
synchronous delivery — matching the reference's own in-process daemon
integration strategy (testing/integration/src/common/daemon.rs).  The
tonic-equivalent wire transport (C++ gRPC/asio) binds underneath in a
later milestone without changing the flow logic.

Messages are (type, payload) tuples; types mirror p2p.proto payload names.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

from kaspa_tpu.consensus.consensus import Consensus, RuleError
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.mempool import MiningManager
from kaspa_tpu.mempool.mempool import MempoolError

# p2p.proto payload types modeled this round
MSG_VERSION = "version"
MSG_VERACK = "verack"
MSG_INV_BLOCK = "invrelayblock"
MSG_REQUEST_BLOCK = "requestrelayblocks"
MSG_BLOCK = "block"
MSG_INV_TXS = "invtransactions"
MSG_REQUEST_TXS = "requesttransactions"
MSG_TX = "transaction"
MSG_REQUEST_IBD_BLOCKS = "requestibdblocks"
MSG_IBD_BLOCKS = "ibdblocks"

PROTOCOL_VERSION = 7


class ProtocolError(Exception):
    """Peer misbehavior that warrants disconnect/ban (flows ProtocolError)."""


@dataclass
class Peer:
    """Router endpoint for one connection (p2p/src/core/router.rs)."""

    node: "Node"
    remote: "Peer | None" = None
    handshaken: bool = False
    inbox: deque = field(default_factory=deque)
    known_blocks: set = field(default_factory=set)
    known_txs: set = field(default_factory=set)

    def send(self, msg_type: str, payload) -> None:
        """Enqueue on the remote peer's inbox and drain it (sync transport)."""
        self.remote.inbox.append((msg_type, payload))
        self.remote.node._drain(self.remote)


class Node:
    """A full node instance: consensus + mempool + flow handlers + hub."""

    def __init__(self, consensus: Consensus, name: str = "node"):
        import threading

        self.name = name
        self.consensus = consensus
        self.mining = MiningManager(consensus)
        self.peers: list = []  # the Hub (p2p/src/core/hub.rs)
        self.orphan_blocks: dict[bytes, Block] = {}  # flowcontext/orphans.rs
        # single-writer discipline: wire reader threads and RPC dispatch all
        # serialize consensus/mempool access through this lock
        self.lock = threading.RLock()

    # --- hub / relay (flow_context.rs on_new_block -> broadcast) ---

    def broadcast_block(self, block: Block) -> None:
        # snapshot: a failed send self-removes the peer from self.peers
        for peer in list(self.peers):
            if block.hash not in peer.known_blocks:
                peer.known_blocks.add(block.hash)
                peer.send(MSG_INV_BLOCK, block.hash)

    def broadcast_tx(self, tx) -> None:
        for peer in list(self.peers):
            if tx.id() not in peer.known_txs:
                peer.known_txs.add(tx.id())
                peer.send(MSG_INV_TXS, [tx.id()])

    def submit_block(self, block: Block) -> str:
        status = self.consensus.validate_and_insert_block(block)
        self.mining.handle_new_block_transactions(block.transactions, self.consensus.get_virtual_daa_score())
        self._try_unorphan(block.hash)
        self.broadcast_block(block)
        return status

    def submit_transaction(self, tx) -> None:
        self.mining.validate_and_insert_transaction(tx)
        self.broadcast_tx(tx)

    # --- flow handlers (protocol/flows/src/v7/) ---

    def _drain(self, peer: Peer) -> None:
        while peer.inbox:
            msg_type, payload = peer.inbox.popleft()
            self._handle(peer, msg_type, payload)

    def _handle(self, peer: Peer, msg_type: str, payload) -> None:
        if msg_type == MSG_VERSION:
            # handshake.rs: version negotiation incl. network match
            if isinstance(payload, dict) and payload.get("network", self.consensus.params.name) != self.consensus.params.name:
                raise ProtocolError(f"network mismatch: {payload.get('network')}")
            if not getattr(peer, "version_sent", True):
                # inbound wire peer: reciprocate with our own version
                peer.version_sent = True
                peer.send(
                    MSG_VERSION,
                    {"protocol_version": PROTOCOL_VERSION, "network": self.consensus.params.name, "listen_port": 0},
                )
            peer.send(MSG_VERACK, PROTOCOL_VERSION)
        elif msg_type == MSG_VERACK:
            peer.handshaken = True
        elif msg_type == "ping":
            peer.send("pong", payload)
        elif msg_type == "pong":
            pass
        elif msg_type == MSG_INV_BLOCK:
            # blockrelay/flow.rs: request unknown relay blocks
            if not self.consensus.storage.statuses.is_valid(payload) and payload not in self.orphan_blocks:
                peer.send(MSG_REQUEST_BLOCK, [payload])
        elif msg_type == MSG_REQUEST_BLOCK:
            for h in payload:
                if self.consensus.storage.block_transactions.has(h):
                    header = self.consensus.storage.headers.get(h)
                    txs = self.consensus.storage.block_transactions.get(h)
                    peer.send(MSG_BLOCK, Block(header, txs))
        elif msg_type == MSG_BLOCK:
            self._on_relay_block(peer, payload)
        elif msg_type == MSG_INV_TXS:
            unknown = [t for t in payload if not self.mining.mempool.has(t)]
            if unknown:
                peer.send(MSG_REQUEST_TXS, unknown)
        elif msg_type == MSG_REQUEST_TXS:
            for txid in payload:
                entry = self.mining.mempool.get(txid)
                if entry is not None:
                    peer.send(MSG_TX, entry.tx)
        elif msg_type == MSG_TX:
            from kaspa_tpu.consensus.processes.transaction_validator import TxRuleError

            peer.known_txs.add(payload.id())
            try:
                self.mining.validate_and_insert_transaction(payload)
            except (MempoolError, TxRuleError):
                return  # relay rejections are not punished unless malformed
            self.broadcast_tx(payload)
        elif msg_type == MSG_REQUEST_IBD_BLOCKS:
            # serve blocks above the requested low hashes in topological order
            blocks = self._blocks_in_topological_order()
            have = set(payload)
            peer.send(MSG_IBD_BLOCKS, [b for b in blocks if b.hash not in have])
        elif msg_type == MSG_IBD_BLOCKS:
            for block in payload:
                try:
                    self.consensus.validate_and_insert_block(block)
                except RuleError:
                    pass

    def _on_relay_block(self, peer: Peer, block: Block) -> None:
        peer.known_blocks.add(block.hash)  # sender has it: don't echo the inv back
        parents = block.header.direct_parents()
        missing = [p for p in parents if not self.consensus.storage.headers.has(p)]
        if missing:
            # orphan: request missing ancestors (orphan resolution, flow.rs)
            self.orphan_blocks[block.hash] = block
            peer.send(MSG_REQUEST_BLOCK, missing)
            return
        try:
            self.consensus.validate_and_insert_block(block)
        except RuleError:
            return  # invalid relay: reference would score/ban the peer
        self.mining.handle_new_block_transactions(block.transactions, self.consensus.get_virtual_daa_score())
        self._try_unorphan(block.hash)
        self.broadcast_block(block)

    def _try_unorphan(self, new_hash: bytes) -> None:
        """revalidate_orphans: process orphans whose parents arrived."""
        progress = True
        while progress:
            progress = False
            for h, block in list(self.orphan_blocks.items()):
                if all(self.consensus.storage.headers.has(p) for p in block.header.direct_parents()):
                    del self.orphan_blocks[h]
                    try:
                        self.consensus.validate_and_insert_block(block)
                        self.broadcast_block(block)
                        progress = True
                    except RuleError:
                        pass

    def _blocks_in_topological_order(self) -> list[Block]:
        """All block bodies sorted by (blue_work, hash) — a topological order
        since ancestors always have strictly smaller blue work."""
        gd = self.consensus.storage.ghostdag
        hashes = [
            h
            for h in self.consensus.storage.headers._headers
            if h != self.consensus.params.genesis.hash and self.consensus.storage.block_transactions.has(h)
        ]
        hashes.sort(key=lambda h: (gd.get_blue_work(h), h))
        return [
            Block(self.consensus.storage.headers.get(h), self.consensus.storage.block_transactions.get(h))
            for h in hashes
        ]

    def ibd_from(self, peer: Peer) -> None:
        """Naive full-sync IBD (ibd/flow.rs Sync path; proof-based sync is a
        later milestone): request everything above what we have."""
        have = [h for h in self.consensus.storage.headers._headers]
        peer.send(MSG_REQUEST_IBD_BLOCKS, have)


def connect(a: Node, b: Node) -> tuple[Peer, Peer]:
    """Wire two nodes with a bidirectional in-process connection + handshake."""
    pa = Peer(node=a)  # a's endpoint talking to b
    pb = Peer(node=b)
    pa.remote = pb
    pb.remote = pa
    a.peers.append(pa)
    b.peers.append(pb)
    pa.send(MSG_VERSION, {"protocol_version": PROTOCOL_VERSION, "network": a.consensus.params.name, "listen_port": 0})
    pb.send(MSG_VERSION, {"protocol_version": PROTOCOL_VERSION, "network": b.consensus.params.name, "listen_port": 0})
    return pa, pb
