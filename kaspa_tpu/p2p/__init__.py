from kaspa_tpu.p2p.node import Node, connect  # noqa: F401
