"""AddressManager + ConnectionManager: peer bookkeeping, banning, outbound
connection maintenance.

Reference: components/addressmanager/src/lib.rs (address store with
connection-failure prioritization, 24h IP bans, weighted random iteration)
and components/connectionmanager/src/lib.rs (outbound target maintenance,
permanent connection requests with retry backoff).  DNS seeding is
implemented (`dns_seed` below, resolving per-network seed hostnames into
the store), and UPnP port mapping lives in `upnp.py` (daemon `--upnp`):
the mapped external address joins the store for gossip but is tracked in
`local_addresses` so the connection manager never dials the node itself.
"""

from __future__ import annotations

import random
import threading

from kaspa_tpu.utils.sync import ranked_lock
import time
from dataclasses import dataclass, field

MAX_ADDRESSES = 4096
MAX_CONNECTION_FAILED_COUNT = 3
MAX_BANNED_TIME_MS = 24 * 60 * 60 * 1000


@dataclass(frozen=True)
class NetAddress:
    ip: str
    port: int

    def __str__(self) -> str:
        return f"{self.ip}:{self.port}"

    @classmethod
    def parse(cls, s: str) -> "NetAddress":
        host, port = s.rsplit(":", 1)
        return cls(host, int(port))


@dataclass
class _Entry:
    address: NetAddress
    connection_failed_count: int = 0


class AddressManager:
    """Known-peer address book with failure-weighted sampling and bans."""

    def __init__(self, now_ms=None, seed: int | None = None):
        self._now_ms = now_ms or (lambda: int(time.time() * 1000))
        self._store: dict[NetAddress, _Entry] = {}
        self._banned: dict[str, int] = {}  # ip -> ban timestamp ms
        # our own publicly routable addresses: gossiped, never dialed
        self.local_addresses: set[NetAddress] = set()
        self._lock = ranked_lock("p2p.addressbook")
        # sampling jitter: folded with --seed so seeded runs (swarm drills)
        # iterate the address book in a reproducible order
        self._rng = random.Random(0xADD7 if seed is None else (0xADD7 ^ seed))

    def add_local_address(self, address: NetAddress) -> None:
        """Register one of OUR publicly routable addresses: gossiped to
        peers like any stored address but excluded from outbound dialing
        (the reference keeps local addresses in a separate non-dialable
        list, addressmanager lib.rs local_net_addresses)."""
        with self._lock:
            self.local_addresses.add(address)
        self.add_address(address)

    def add_address(self, address: NetAddress) -> None:
        with self._lock:
            if self.is_banned(address.ip) or address in self._store:
                return
            if len(self._store) >= MAX_ADDRESSES:
                # evict the most-failed address to make room
                victim = max(self._store.values(), key=lambda e: e.connection_failed_count)
                del self._store[victim.address]
            self._store[address] = _Entry(address)

    def remove(self, address: NetAddress) -> None:
        with self._lock:
            self._store.pop(address, None)

    def mark_connection_failure(self, address: NetAddress) -> None:
        with self._lock:
            e = self._store.get(address)
            if e is None:
                return
            e.connection_failed_count += 1
            if e.connection_failed_count > MAX_CONNECTION_FAILED_COUNT:
                del self._store[address]

    def mark_connection_success(self, address: NetAddress) -> None:
        with self._lock:
            e = self._store.get(address)
            if e is not None:
                e.connection_failed_count = 0

    def iterate_prioritized_random_addresses(self, exclude: set[NetAddress] = frozenset()):
        """Weighted random order: weight 64^(3 - failures) (lib.rs:438)."""
        with self._lock:
            entries = [e for a, e in self._store.items() if a not in exclude]
        weights = [64.0 ** (MAX_CONNECTION_FAILED_COUNT - min(e.connection_failed_count, 3)) for e in entries]
        out = []
        pool = list(zip(entries, weights))
        while pool:
            total = sum(w for _, w in pool)
            pick = self._rng.random() * total
            for i, (e, w) in enumerate(pool):
                pick -= w
                if pick <= 0:
                    out.append(e.address)
                    pool.pop(i)
                    break
            else:
                out.append(pool.pop()[0].address)
        return out

    def dns_seed(self, seeds: list[str], default_port: int) -> int:
        """Resolve seed hostnames into the address book (flow_context
        dnsseed bootstrap; the reference resolves its per-network seeder
        list when the book runs low).  Returns the number of addresses
        added; resolution failures are skipped, never fatal."""
        import socket as _socket

        added = 0
        for seed in seeds:
            host, _, port = seed.partition(":")
            try:
                infos = _socket.getaddrinfo(host, int(port) if port else default_port, type=_socket.SOCK_STREAM)
            except (OSError, ValueError):
                continue
            for info in infos:
                ip = info[4][0]
                addr = NetAddress(ip, info[4][1])
                if not self.is_banned(ip):
                    self.add_address(addr)
                    added += 1
        return added

    def get_all_addresses(self) -> list[NetAddress]:
        with self._lock:
            return list(self._store)

    # --- banning ---------------------------------------------------------

    def ban(self, ip: str) -> None:
        with self._lock:
            self._banned[ip] = self._now_ms()
            for a in [a for a in self._store if a.ip == ip]:
                del self._store[a]

    def unban(self, ip: str) -> None:
        with self._lock:
            self._banned.pop(ip, None)

    def is_banned(self, ip: str) -> bool:
        with self._lock:
            ts = self._banned.get(ip)
            if ts is None:
                return False
            if self._now_ms() - ts >= MAX_BANNED_TIME_MS:
                del self._banned[ip]
                return False
            return True

    def get_all_banned_addresses(self) -> list[str]:
        with self._lock:
            return [ip for ip in list(self._banned) if self.is_banned(ip)]


# failed-dial backoff: 2s, 4s, 8s, ... capped at 5 min, each delay jittered
# by a uniform 0.5x-1.5x factor so a network blip doesn't resynchronize
# every node's reconnect storm onto the same tick
RECONNECT_BACKOFF_BASE = 2.0
RECONNECT_BACKOFF_MAX = 300.0


class ConnectionManager:
    """Maintains outbound connections toward a target count.

    connectionmanager/src/lib.rs: a periodic tick compares live outbound
    peers to `outbound_target`, dials prioritized-random known addresses,
    and retries `permanent` requests (--connect peers) with backoff.
    """

    def __init__(
        self,
        node,
        amgr: AddressManager,
        outbound_target: int = 8,
        tick_seconds: float = 30.0,
        seed: int | None = None,
    ):
        self.node = node  # kaspa_tpu.p2p.node.Node with .peers
        self.amgr = amgr
        self.outbound_target = outbound_target
        self.tick_seconds = tick_seconds
        self._permanent: dict[NetAddress, int] = {}  # address -> retry attempts
        # per-address reconnect gate: monotonic instant before which the
        # address must not be redialed (exponential in consecutive failures)
        self._next_dial: dict[NetAddress, float] = {}
        self._fail_counts: dict[NetAddress, int] = {}
        # backoff jitter: folded with --seed so seeded runs draw the same
        # delays (fleet decorrelation survives — each node folds its own id)
        self._rng = random.Random(0xBACC0FF if seed is None else (0xBACC0FF ^ seed))
        self._clock = time.monotonic
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = ranked_lock("p2p.connmgr")

    def add_connection_request(self, address: NetAddress, is_permanent: bool = False) -> None:
        with self._lock:
            if is_permanent:
                self._permanent.setdefault(address, 0)
        self._tick()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True, name="connmgr")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_seconds):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — keep the maintenance loop alive
                pass

    def _connected_addresses(self) -> set[NetAddress]:
        out = set()
        for peer in list(self.node.peers):
            for attr in ("peer_address", "advertised_address"):
                addr = getattr(peer, attr, None)
                if addr is not None:
                    out.add(addr)
        return out

    def _dial(self, address: NetAddress) -> bool:
        from kaspa_tpu.p2p import transport

        try:
            peer = transport.connect_outbound(self.node, str(address))
            peer.peer_address = address
            self.amgr.mark_connection_success(address)
            # per-peer IBD flow kicks off on connect (flow registration);
            # ibd_from only sends the chain-info request — no lock needed,
            # and _on_chain_info no-ops when the peer has nothing we lack
            self.node.ibd_from(peer)
            return True
        except (OSError, ConnectionError):
            self.amgr.mark_connection_failure(address)
            return False

    def _may_dial(self, address: NetAddress, now: float) -> bool:
        with self._lock:
            return self._next_dial.get(address, 0.0) <= now

    def _note_dial(self, address: NetAddress, ok: bool) -> None:
        """Update the per-address reconnect gate after a dial attempt."""
        with self._lock:
            if ok:
                self._next_dial.pop(address, None)
                self._fail_counts.pop(address, None)
                return
            n = self._fail_counts.get(address, 0)
            self._fail_counts[address] = n + 1
            delay = min(RECONNECT_BACKOFF_BASE * (2.0 ** n), RECONNECT_BACKOFF_MAX)
            delay *= 0.5 + self._rng.random()  # jitter: decorrelate the fleet
            self._next_dial[address] = self._clock() + delay

    def _tick(self) -> None:
        now = self._clock()
        connected = self._connected_addresses()
        # permanent requests first (exponential backoff by attempt count)
        with self._lock:
            pending = [a for a in self._permanent if a not in connected]
        for addr in pending:
            if self.amgr.is_banned(addr.ip) or not self._may_dial(addr, now):
                continue
            ok = self._dial(addr)
            self._note_dial(addr, ok)
            with self._lock:
                self._permanent[addr] = 0 if ok else self._permanent[addr] + 1
        # fill toward the outbound target from the address book
        missing = self.outbound_target - len(self._connected_addresses())
        if missing <= 0:
            return
        for addr in self.amgr.iterate_prioritized_random_addresses(exclude=connected):
            if missing <= 0:
                break
            if self.amgr.is_banned(addr.ip) or addr in self.amgr.local_addresses:
                continue  # never dial our own mapped/advertised address
            if not self._may_dial(addr, now):
                continue
            ok = self._dial(addr)
            self._note_dial(addr, ok)
            if ok:
                missing -= 1
