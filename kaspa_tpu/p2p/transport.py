"""P2P socket transport: framed binary messages between OS processes.

The reference's connection layer (protocol/p2p/src/core/connection_handler.rs
over tonic gRPC streams + Router per peer) as a thread-per-connection TCP
server speaking the frames of p2p/wire.py.  The flow logic stays in
p2p/node.Node — a WirePeer exposes the same ``send(msg_type, payload)``
surface as the in-process Peer, so every handler runs unchanged over the
wire.

Concurrency: each connection gets a reader thread and a writer thread; all
flow handling is serialized through ``node.lock`` (the node objects are
single-writer, the discipline the reference gets from consensus sessions +
the tokio runtime).  Sends only *enqueue* — socket writes happen on the
writer thread so a handler never blocks on peer backpressure while holding
``node.lock`` (two nodes serving each other large IBD payloads would
otherwise deadlock once both TCP buffers filled).  Mirrors the reference
Router's bounded mpsc outgoing lane (p2p/src/core/router.rs); a peer whose
queue overflows is dropped as too-slow.
"""

from __future__ import annotations

import os
import queue
import socket
import threading
from time import perf_counter_ns

_SEND_QUEUE_LIMIT = 4096  # frames; overflow => drop the peer (slow consumer)

from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.p2p import wire
from kaspa_tpu.p2p.node import MIN_PROTOCOL_VERSION, MSG_VERSION, Node, ProtocolError
from kaspa_tpu.resilience import faults as fault_mod
from kaspa_tpu.resilience.faults import FAULTS, FaultInjected


class WireMetrics:
    """The transport's instrument set, bound to one registry (or scope).

    Codec cost only (socket IO excluded): encode is timed around
    codec.encode in send(), decode around codec.decode in the reader loop —
    blocking recv time would otherwise swamp the histogram.  Both wire
    implementations (custom frames and protobuf/gRPC) feed the SAME
    instruments so dashboards compare codecs without relabeling.

    One process-global default instance serves the daemon (one node per
    process).  A multi-node host — the swarm drill — hangs a scoped
    instance on ``node.wire_metrics`` so each node's relay accounting
    (``p2p_msgs_rx`` per message type, the amplification budget's input)
    lands in its own namespace instead of one shared counter.
    """

    __slots__ = ("enc_time", "dec_time", "frames_tx", "frames_rx", "bytes_tx", "bytes_rx", "msgs_tx", "msgs_rx")

    def __init__(self, registry=REGISTRY):
        self.enc_time = registry.histogram("p2p_frame_encode_seconds", help="wire frame encode time (codec only)")
        self.dec_time = registry.histogram("p2p_frame_decode_seconds", help="wire payload decode time (codec only)")
        self.frames_tx = registry.counter("p2p_frames_tx", help="frames enqueued for send")
        self.frames_rx = registry.counter("p2p_frames_rx", help="frames received and decoded")
        self.bytes_tx = registry.counter("p2p_bytes_tx", help="frame bytes enqueued for send")
        self.bytes_rx = registry.counter("p2p_bytes_rx", help="frame bytes received (incl. headers)")
        self.msgs_tx = registry.counter_family("p2p_msgs_tx", "type", help="messages sent by flow message type")
        self.msgs_rx = registry.counter_family("p2p_msgs_rx", "type", help="messages received by flow message type")


_DEFAULT_METRICS = WireMetrics(REGISTRY)


def wire_metrics_for(node) -> WireMetrics:
    """The node's own instrument set if it carries one, else the global."""
    m = getattr(node, "wire_metrics", None)
    return m if m is not None else _DEFAULT_METRICS


class CustomWireCodec:
    """The canonical serde wire of p2p/wire.py (magic|type|len|payload)."""

    name = "custom"

    def encode(self, msg_type: str, payload) -> bytes:
        return wire.encode_frame(msg_type, payload)

    def read_frame(self, read_exactly) -> tuple[object, bytes, int]:
        """Blocking read of one frame -> (decode meta, body, wire bytes).

        Kept separate from :meth:`decode` so the reader loop can time codec
        work alone — socket waits never enter the decode histogram."""
        type_id, plen = wire.decode_frame(read_exactly(7))
        return type_id, read_exactly(plen), 7 + plen

    def decode(self, meta, body: bytes) -> tuple[str, object]:
        return wire.decode_payload(meta, body)


class GrpcProtoCodec:
    """Reference-compatible wire: KaspadMessage protobuf in gRPC framing.

    Byte-compatible with what the reference's tonic stack writes inside
    HTTP/2 DATA frames (p2p/proto/framing.py has the layout); the payload
    bytes are the vendored KaspadMessage schema.  Same reader/writer
    machinery, same flow layer — only the bytes on the socket change.
    """

    name = "proto"

    def __init__(self):
        # deferred import: kaspa_tpu.p2p.proto.codec imports node constants,
        # and transport is imported early by the daemon
        from kaspa_tpu.p2p.proto import framing
        from kaspa_tpu.p2p.proto import codec as proto_codec

        self._framing = framing
        self._codec = proto_codec

    def encode(self, msg_type: str, payload) -> bytes:
        return self._framing.encode_grpc_frame(self._codec.encode_kaspad_message(msg_type, payload))

    def read_frame(self, read_exactly) -> tuple[object, bytes, int]:
        n = self._framing.decode_grpc_prefix(read_exactly(self._framing.GRPC_FRAME_OVERHEAD))
        return None, read_exactly(n), self._framing.GRPC_FRAME_OVERHEAD + n

    def decode(self, _meta, body: bytes) -> tuple[str, object]:
        return self._codec.decode_kaspad_message(body)


def get_codec(name: str):
    """Wire selector for the daemon's ``--p2p-proto`` flag."""
    if name == "custom":
        return CustomWireCodec()
    if name == "proto":
        return GrpcProtoCodec()
    raise ValueError(f"unknown p2p wire codec {name!r} (expected 'custom' or 'proto')")


class WirePeer:
    """Router endpoint over a socket (p2p/src/core/router.rs)."""

    def __init__(self, node: Node, sock: socket.socket, outbound: bool, codec=None):
        self.node = node
        self.sock = sock
        self.outbound = outbound
        self.codec = codec if codec is not None else CustomWireCodec()
        self.metrics = wire_metrics_for(node)
        # the remote's version-handshake identity nonce (node._handle sets
        # it on VERSION receipt); the LINKS partition plane keys on it
        self.remote_id = None
        try:
            ip, port = sock.getpeername()[:2]
            from kaspa_tpu.p2p.address_manager import NetAddress

            self.peer_address = NetAddress(ip, port)
        except OSError:
            self.peer_address = None
        self.version_sent = outbound  # inbound reciprocates on VERSION receipt
        self.handshaken = False
        self.misbehavior_score = 0
        # a half-open socket (SYN accepted, VERSION never arrives) must not
        # pin a reader thread forever; after the handshake the read deadline
        # relaxes to read_timeout (0 = disabled — block indefinitely)
        self.handshake_timeout = float(os.environ.get("KASPA_TPU_P2P_HANDSHAKE_TIMEOUT", "15"))
        self.read_timeout = float(os.environ.get("KASPA_TPU_P2P_READ_TIMEOUT", "0"))
        # tier floor until the handshake negotiates (node._handle sets it)
        self.protocol_version = MIN_PROTOCOL_VERSION
        self.known_blocks: set = set()
        self.known_txs: set = set()
        self.alive = True
        self._outq: queue.Queue = queue.Queue(maxsize=_SEND_QUEUE_LIMIT)
        self._thread: threading.Thread | None = None
        self._writer: threading.Thread | None = None

    def send(self, msg_type: str, payload) -> None:
        if not self.alive:
            return
        links = fault_mod.LINKS
        if links.active and links.drop(getattr(self.node, "id", None), self.remote_id):
            # severed link: the frame is black-holed before it is even
            # encoded — the sender's relay state (known_blocks dedup)
            # still believes it left, exactly like real packet loss
            FAULTS.fire("p2p.partition")
            return
        t0 = perf_counter_ns()
        frame = self.codec.encode(msg_type, payload)
        self.metrics.enc_time.observe((perf_counter_ns() - t0) * 1e-9)
        act = FAULTS.fire("p2p.send")
        if act is not None:
            if act.mode == "disconnect":
                self.close()
                return
            frame = fault_mod.mangle_frame(frame, act)
            if frame is None:  # drop: the frame silently never leaves
                return
        self.metrics.frames_tx.inc()
        self.metrics.bytes_tx.inc(len(frame))
        self.metrics.msgs_tx.inc(msg_type)
        try:
            self._outq.put_nowait(frame)
        except queue.Full:
            self.close()

    def flush(self, timeout: float = 1.0) -> bool:
        """Block until every frame enqueued so far has hit the socket.

        Implemented as a sentinel Event that rides the FIFO behind the
        pending frames; the writer thread sets it once everything ahead of
        it has been sendall()'d.  Bounded wait: a wedged peer must not be
        able to pin the caller (returns False on timeout/overflow)."""
        if not self.alive:
            return False
        done = threading.Event()
        try:
            self._outq.put_nowait(done)
        except queue.Full:
            return False
        return done.wait(timeout)

    def _writer_loop(self) -> None:
        try:
            while True:
                frame = self._outq.get()
                if frame is None:
                    return
                if isinstance(frame, threading.Event):
                    frame.set()  # flush barrier: everything ahead is on the wire
                    continue
                self.sock.sendall(frame)
        except OSError:
            pass
        finally:
            self.close()

    def _score(self, peer, reason: str, points: int) -> bool:
        # test doubles and minimal node stubs don't carry the misbehavior
        # ledger; treat them as never banning
        score = getattr(self.node, "score_misbehavior", None)
        return bool(score(peer, reason, points)) if score is not None else False

    def _read_exactly(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    def _reader_loop(self) -> None:
        try:
            # handshake deadline: socket.timeout is an OSError subclass, so
            # an expired deadline lands in the handler below and closes the
            # peer — the reference's handshake timeout in connection_handler
            self.sock.settimeout(self.handshake_timeout or None)
            steady = False
            while self.alive:
                act = FAULTS.fire("p2p.recv")
                if act is not None and act.mode == "disconnect":
                    raise ConnectionError("injected disconnect")
                # frame read and payload decode are split so only codec work
                # is timed — the header/body reads block on the peer
                meta, body, nbytes = self.codec.read_frame(self._read_exactly)
                t0 = perf_counter_ns()
                try:
                    msg_type, payload = self.codec.decode(meta, body)
                except Exception:  # noqa: BLE001 - body didn't decode but the
                    # frame header did, so the stream is still in sync: score
                    # the peer and keep reading.  A repeat offender crosses
                    # the ban threshold and is dropped + address-banned.
                    if self._score(self, "malformed_frame", 40):
                        raise ConnectionError("peer banned for malformed frames") from None
                    continue
                self.metrics.dec_time.observe((perf_counter_ns() - t0) * 1e-9)
                self.metrics.frames_rx.inc()
                self.metrics.bytes_rx.inc(nbytes)
                self.metrics.msgs_rx.inc(msg_type)
                with self.node.lock:
                    # graftlint: allow(blocking-under-lock) -- every p2p message is handled under the node lock (the node's serialization point); IBD batch inserts legitimately wait on verify futures there
                    self.node._handle(self, msg_type, payload)
                if self.handshaken and not steady:
                    steady = True
                    self.sock.settimeout(self.read_timeout or None)
        except (ConnectionError, OSError):
            pass
        except ProtocolError as e:
            # protocol violations score per the error's own weight (benign
            # handshake mismatches carry 0), and the peer is told WHY
            # before dropping it (p2p.proto RejectMessage)
            points = getattr(e, "points", 100)
            if points:
                self._score(self, "protocol_error", points)
            from kaspa_tpu.p2p.node import MSG_REJECT

            try:
                self.send(MSG_REJECT, str(e))
                # the finally-close below would otherwise race the writer
                # thread and RST the socket before the reject frame leaves
                self.flush()
            except Exception:  # noqa: BLE001 - socket may already be gone
                pass
        except Exception:  # noqa: BLE001 - wire boundary: malformed frames,
            # codec decode errors, or consensus rejections from adversarial
            # payloads all mean "drop the peer", with misbehavior points so
            # a repeat offender graduates to a ban
            self._score(self, "malformed_frame", 40)
        finally:
            self.close()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._reader_loop, daemon=True, name="p2p-reader")
        self._thread.start()
        self._writer = threading.Thread(target=self._writer_loop, daemon=True, name="p2p-writer")
        self._writer.start()

    def close(self) -> None:
        if not self.alive:
            return
        self.alive = False
        try:
            self._outq.put_nowait(None)  # unblock the writer thread
        except queue.Full:
            pass  # writer will hit the closed socket and exit
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()
        with self.node.lock:
            if self in self.node.peers:
                self.node.peers.remove(self)

    def wait_handshaken(self, timeout: float = 10.0) -> bool:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.handshaken:
                return True
            if not self.alive:
                return False  # peer rejected us (e.g. self-connection)
            time.sleep(0.01)
        return False


class P2PServer:
    """Listener accepting inbound peers (connection_handler.rs serve)."""

    def __init__(self, node: Node, host: str = "127.0.0.1", port: int = 0, address_manager=None, codec=None):
        self.node = node
        self.address_manager = address_manager  # inbound ban enforcement
        self.codec = codec if codec is not None else CustomWireCodec()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.address = f"{host}:{self._sock.getsockname()[1]}"
        self._accept_thread: threading.Thread | None = None
        self._running = False

    def start(self) -> None:
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True, name="p2p-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return
            if self.address_manager is not None and self.address_manager.is_banned(addr[0]):
                sock.close()
                continue
            # codecs are stateless; the server's instance is shared by peers
            peer = WirePeer(self.node, sock, outbound=False, codec=self.codec)
            with self.node.lock:
                self.node.peers.append(peer)
            peer.start()

    def stop(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass


def connect_outbound(node: Node, address: str, timeout: float = 10.0, codec=None) -> WirePeer:
    """Dial a peer, run the version/verack handshake, return the live peer.

    Both ends must speak the same wire (``codec``): like the reference,
    wire selection is deployment configuration, not negotiated in-band —
    the version handshake only negotiates the flow tier."""
    host, port = address.rsplit(":", 1)
    try:
        # injected dial failure (mode "error"): presents as the failure the
        # caller already handles so the connect-retry path absorbs it
        FAULTS.fire("p2p.link_drop")
    except FaultInjected as e:
        raise ConnectionError(f"injected link drop dialing {address}") from e
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    # the reader loop owns the socket deadline from here (handshake_timeout,
    # then read_timeout once handshaken)
    peer = WirePeer(node, sock, outbound=True, codec=codec)
    with node.lock:
        node.peers.append(peer)
    peer.start()
    peer.send(
        MSG_VERSION,
        {
            "protocol_version": node.protocol_version,
            "network": node.consensus.params.name,
            "listen_port": node.listen_port,
            "id": node.id,
        },
    )
    if not peer.wait_handshaken(timeout):
        peer.close()
        raise ConnectionError(f"handshake with {address} timed out")
    return peer
