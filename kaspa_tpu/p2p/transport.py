"""P2P socket transport: framed binary messages between OS processes.

The reference's connection layer (protocol/p2p/src/core/connection_handler.rs
over tonic gRPC streams + Router per peer) as a thread-per-connection TCP
server speaking the frames of p2p/wire.py.  The flow logic stays in
p2p/node.Node — a WirePeer exposes the same ``send(msg_type, payload)``
surface as the in-process Peer, so every handler runs unchanged over the
wire.

Concurrency: each connection gets a reader thread and a writer thread; all
flow handling is serialized through ``node.lock`` (the node objects are
single-writer, the discipline the reference gets from consensus sessions +
the tokio runtime).  Sends only *enqueue* — socket writes happen on the
writer thread so a handler never blocks on peer backpressure while holding
``node.lock`` (two nodes serving each other large IBD payloads would
otherwise deadlock once both TCP buffers filled).  Mirrors the reference
Router's bounded mpsc outgoing lane (p2p/src/core/router.rs); a peer whose
queue overflows is dropped as too-slow.
"""

from __future__ import annotations

import queue
import socket
import threading
from time import perf_counter_ns

_SEND_QUEUE_LIMIT = 4096  # frames; overflow => drop the peer (slow consumer)

from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.p2p import wire
from kaspa_tpu.p2p.node import MIN_PROTOCOL_VERSION, MSG_VERSION, Node, ProtocolError

# codec cost only (socket IO excluded): encode is timed around
# wire.encode_frame in send(), decode around wire.decode_payload in the
# reader loop — blocking recv time would otherwise swamp the histogram
_ENC_TIME = REGISTRY.histogram("p2p_frame_encode_seconds", help="wire frame encode time (codec only)")
_DEC_TIME = REGISTRY.histogram("p2p_frame_decode_seconds", help="wire payload decode time (codec only)")
_FRAMES_TX = REGISTRY.counter("p2p_frames_tx", help="frames enqueued for send")
_FRAMES_RX = REGISTRY.counter("p2p_frames_rx", help="frames received and decoded")
_BYTES_TX = REGISTRY.counter("p2p_bytes_tx", help="frame bytes enqueued for send")
_BYTES_RX = REGISTRY.counter("p2p_bytes_rx", help="frame bytes received (incl. headers)")


class WirePeer:
    """Router endpoint over a socket (p2p/src/core/router.rs)."""

    def __init__(self, node: Node, sock: socket.socket, outbound: bool):
        self.node = node
        self.sock = sock
        self.outbound = outbound
        try:
            ip, port = sock.getpeername()[:2]
            from kaspa_tpu.p2p.address_manager import NetAddress

            self.peer_address = NetAddress(ip, port)
        except OSError:
            self.peer_address = None
        self.version_sent = outbound  # inbound reciprocates on VERSION receipt
        self.handshaken = False
        # tier floor until the handshake negotiates (node._handle sets it)
        self.protocol_version = MIN_PROTOCOL_VERSION
        self.known_blocks: set = set()
        self.known_txs: set = set()
        self.alive = True
        self._outq: queue.Queue = queue.Queue(maxsize=_SEND_QUEUE_LIMIT)
        self._thread: threading.Thread | None = None
        self._writer: threading.Thread | None = None

    def send(self, msg_type: str, payload) -> None:
        if not self.alive:
            return
        t0 = perf_counter_ns()
        frame = wire.encode_frame(msg_type, payload)
        _ENC_TIME.observe((perf_counter_ns() - t0) * 1e-9)
        _FRAMES_TX.inc()
        _BYTES_TX.inc(len(frame))
        try:
            self._outq.put_nowait(frame)
        except queue.Full:
            self.close()

    def flush(self, timeout: float = 1.0) -> bool:
        """Block until every frame enqueued so far has hit the socket.

        Implemented as a sentinel Event that rides the FIFO behind the
        pending frames; the writer thread sets it once everything ahead of
        it has been sendall()'d.  Bounded wait: a wedged peer must not be
        able to pin the caller (returns False on timeout/overflow)."""
        if not self.alive:
            return False
        done = threading.Event()
        try:
            self._outq.put_nowait(done)
        except queue.Full:
            return False
        return done.wait(timeout)

    def _writer_loop(self) -> None:
        try:
            while True:
                frame = self._outq.get()
                if frame is None:
                    return
                if isinstance(frame, threading.Event):
                    frame.set()  # flush barrier: everything ahead is on the wire
                    continue
                self.sock.sendall(frame)
        except OSError:
            pass
        finally:
            self.close()

    def _read_exactly(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("peer closed")
            buf += chunk
        return buf

    def _reader_loop(self) -> None:
        try:
            while self.alive:
                # read_message() inlined so only decode_payload (the codec
                # work) is timed — the header/body reads block on the peer
                type_id, plen = wire.decode_frame(self._read_exactly(7))
                body = self._read_exactly(plen)
                t0 = perf_counter_ns()
                msg_type, payload = wire.decode_payload(type_id, body)
                _DEC_TIME.observe((perf_counter_ns() - t0) * 1e-9)
                _FRAMES_RX.inc()
                _BYTES_RX.inc(7 + plen)
                with self.node.lock:
                    self.node._handle(self, msg_type, payload)
        except (ConnectionError, OSError):
            pass
        except ProtocolError as e:
            # tell the peer WHY before dropping it (p2p.proto RejectMessage)
            from kaspa_tpu.p2p.node import MSG_REJECT

            try:
                self.send(MSG_REJECT, str(e))
                # the finally-close below would otherwise race the writer
                # thread and RST the socket before the reject frame leaves
                self.flush()
            except Exception:  # noqa: BLE001 - socket may already be gone
                pass
        except Exception:  # noqa: BLE001 - wire boundary: malformed frames,
            # codec decode errors, or consensus rejections from adversarial
            # payloads all mean "drop the peer" (reference would score/ban)
            pass
        finally:
            self.close()

    def start(self) -> None:
        self._thread = threading.Thread(target=self._reader_loop, daemon=True, name="p2p-reader")
        self._thread.start()
        self._writer = threading.Thread(target=self._writer_loop, daemon=True, name="p2p-writer")
        self._writer.start()

    def close(self) -> None:
        if not self.alive:
            return
        self.alive = False
        try:
            self._outq.put_nowait(None)  # unblock the writer thread
        except queue.Full:
            pass  # writer will hit the closed socket and exit
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()
        with self.node.lock:
            if self in self.node.peers:
                self.node.peers.remove(self)

    def wait_handshaken(self, timeout: float = 10.0) -> bool:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.handshaken:
                return True
            if not self.alive:
                return False  # peer rejected us (e.g. self-connection)
            time.sleep(0.01)
        return False


class P2PServer:
    """Listener accepting inbound peers (connection_handler.rs serve)."""

    def __init__(self, node: Node, host: str = "127.0.0.1", port: int = 0, address_manager=None):
        self.node = node
        self.address_manager = address_manager  # inbound ban enforcement
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen()
        self.address = f"{host}:{self._sock.getsockname()[1]}"
        self._accept_thread: threading.Thread | None = None
        self._running = False

    def start(self) -> None:
        self._running = True
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True, name="p2p-accept")
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while self._running:
            try:
                sock, addr = self._sock.accept()
            except OSError:
                return
            if self.address_manager is not None and self.address_manager.is_banned(addr[0]):
                sock.close()
                continue
            peer = WirePeer(self.node, sock, outbound=False)
            with self.node.lock:
                self.node.peers.append(peer)
            peer.start()

    def stop(self) -> None:
        self._running = False
        try:
            self._sock.close()
        except OSError:
            pass


def connect_outbound(node: Node, address: str, timeout: float = 10.0) -> WirePeer:
    """Dial a peer, run the version/verack handshake, return the live peer."""
    host, port = address.rsplit(":", 1)
    sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.settimeout(None)
    peer = WirePeer(node, sock, outbound=True)
    with node.lock:
        node.peers.append(peer)
    peer.start()
    peer.send(
        MSG_VERSION,
        {
            "protocol_version": node.protocol_version,
            "network": node.consensus.params.name,
            "listen_port": node.listen_port,
            "id": node.id,
        },
    )
    if not peer.wait_handshaken(timeout):
        peer.close()
        raise ConnectionError(f"handshake with {address} timed out")
    return peer
