"""P2P wire codec: binary frames for every flow message.

The role of `protocol/p2p/proto/{p2p,messages}.proto` + tonic framing in the
reference, over the framework's canonical binary codec (consensus/serde.py)
instead of protobuf.  Frame layout:

    magic(2) | type(1) | payload_len(4, LE) | payload

Payloads are serde-encoded.  The codec is pure (bytes in/out) so the flow
layer and tests use it without sockets; transport.py does the socket IO.
"""

from __future__ import annotations

import io
import struct

from kaspa_tpu.consensus import serde
from kaspa_tpu.p2p.node import (
    MSG_BLOCK,
    MSG_IBD_BLOCKS,
    MSG_IBD_CHAIN_INFO,
    MSG_INV_BLOCK,
    MSG_INV_TXS,
    MSG_BLOCK_BODIES,
    MSG_HEADERS,
    MSG_PP_SMT_CHUNK,
    MSG_PP_UTXO_CHUNK,
    MSG_REJECT,
    MSG_REQUEST_BLOCK_BODIES,
    MSG_REQUEST_HEADERS,
    MSG_REQUEST_PP_SMT,
    MSG_PRUNING_PROOF,
    MSG_REQUEST_BLOCK,
    MSG_REQUEST_IBD_CHAIN_INFO,
    MSG_ADDRESSES,
    MSG_IBD_BLOCK_LOCATOR,
    MSG_REQUEST_ANTIPAST,
    MSG_REQUEST_ADDRESSES,
    MSG_REQUEST_PP_UTXOS,
    MSG_REQUEST_PRUNING_PROOF,
    MSG_REQUEST_TRUSTED_DATA,
    MSG_REQUEST_TXS,
    MSG_TRUSTED_DATA,
    MSG_TX,
    MSG_VERACK,
    MSG_VERSION,
)

MAGIC = b"\x4b\x54"  # "KT"
MAX_FRAME = 1 << 30


class WireError(Exception):
    pass


def _read_exact(r: io.BytesIO, n: int) -> bytes:
    """Fixed-width read that refuses to come up short.  ``BytesIO.read``
    silently returns fewer bytes at EOF, so a truncated adversarial frame
    would otherwise decode into garbage values (zero hashes, flipped
    flags) instead of being rejected at the wire boundary."""
    buf = r.read(n)
    if len(buf) != n:
        raise WireError(f"truncated frame: wanted {n} bytes, got {len(buf)}")
    return buf

MSG_PING = "ping"
MSG_PONG = "pong"

# wire ids (stable protocol surface; gaps reserved for IBD messages)
_TYPE_IDS = {
    MSG_VERSION: 0,
    MSG_VERACK: 1,
    MSG_INV_BLOCK: 2,
    MSG_REQUEST_BLOCK: 3,
    MSG_BLOCK: 4,
    MSG_INV_TXS: 5,
    MSG_REQUEST_TXS: 6,
    MSG_TX: 7,
    MSG_IBD_BLOCKS: 9,
    MSG_PING: 10,
    MSG_PONG: 11,
    MSG_REQUEST_IBD_CHAIN_INFO: 12,
    MSG_IBD_CHAIN_INFO: 13,
    MSG_REQUEST_PRUNING_PROOF: 14,
    MSG_PRUNING_PROOF: 15,
    MSG_REQUEST_TRUSTED_DATA: 16,
    MSG_TRUSTED_DATA: 17,
    MSG_REQUEST_PP_UTXOS: 18,
    MSG_PP_UTXO_CHUNK: 19,
    MSG_IBD_BLOCK_LOCATOR: 20,
    MSG_REQUEST_ADDRESSES: 21,
    MSG_ADDRESSES: 22,
    MSG_REQUEST_ANTIPAST: 23,
    MSG_REQUEST_PP_SMT: 24,
    MSG_PP_SMT_CHUNK: 25,
    MSG_REQUEST_BLOCK_BODIES: 26,
    MSG_BLOCK_BODIES: 27,
    MSG_REQUEST_HEADERS: 28,
    MSG_HEADERS: 29,
    MSG_REJECT: 30,
}

_TYPE_NAMES = {v: k for k, v in _TYPE_IDS.items()}


def _enc_version(p) -> bytes:
    """payload: {protocol_version, network, listen_port, id}"""
    w = io.BytesIO()
    serde.write_varint(w, p["protocol_version"])
    serde.write_bytes(w, p["network"].encode())
    serde.write_varint(w, p.get("listen_port", 0))
    serde.write_varint(w, p.get("id", 0))
    return w.getvalue()


def _dec_version(data: bytes):
    r = io.BytesIO(data)
    return {
        "protocol_version": serde.read_varint(r),
        "network": serde.read_bytes(r).decode(),
        "listen_port": serde.read_varint(r),
        "id": serde.read_varint(r),
    }


def _enc_varint(v: int) -> bytes:
    w = io.BytesIO()
    serde.write_varint(w, v)
    return w.getvalue()


def _dec_varint(data: bytes) -> int:
    return serde.read_varint(io.BytesIO(data))


def _enc_blocks(blocks) -> bytes:
    w = io.BytesIO()
    serde.write_varint(w, len(blocks))
    for b in blocks:
        serde.write_bytes(w, serde.encode_block(b))
    return w.getvalue()


def _dec_blocks_stream(r: io.BytesIO):
    return [serde.decode_block(serde.read_bytes(r)) for _ in range(serde.read_varint(r))]


def _dec_blocks(data: bytes):
    return _dec_blocks_stream(io.BytesIO(data))


def _enc_ibd_chunk(p) -> bytes:
    w = io.BytesIO()
    w.write(_enc_blocks(p["blocks"]))
    w.write(b"\x01" if p["done"] else b"\x00")
    w.write(p["continuation"])
    return w.getvalue()


def _dec_ibd_chunk(data: bytes) -> dict:
    r = io.BytesIO(data)
    blocks = _dec_blocks_stream(r)
    tail = r.read(33)
    if len(tail) != 33:
        raise WireError("truncated IBD chunk (missing done/continuation)")
    return {"blocks": blocks, "done": tail[:1] == b"\x01", "continuation": tail[1:]}


def _enc_empty(_p) -> bytes:
    return b""


def _dec_empty(_d) -> dict:
    return {}


def _enc_chain_info(p) -> bytes:
    w = io.BytesIO()
    w.write(p["sink"])
    serde.write_varint(w, p["sink_blue_work"])
    w.write(p["pruning_point"])
    return w.getvalue()


def _dec_chain_info(data: bytes) -> dict:
    r = io.BytesIO(data)
    sink = r.read(32)
    work = serde.read_varint(r)
    return {"sink": sink, "sink_blue_work": work, "pruning_point": r.read(32)}


def _enc_proof(levels) -> bytes:
    w = io.BytesIO()
    serde.write_varint(w, len(levels))
    for level in levels:
        serde.write_varint(w, len(level))
        for hdr in level:
            serde.write_bytes(w, serde.encode_header(hdr))
    return w.getvalue()


def _dec_proof(data: bytes):
    r = io.BytesIO(data)
    return [
        [serde.decode_header(serde.read_bytes(r)) for _ in range(serde.read_varint(r))]
        for _ in range(serde.read_varint(r))
    ]


def _write_hash_map(w, mapping, write_value) -> None:
    serde.write_varint(w, len(mapping))
    for h in sorted(mapping):
        w.write(h)
        write_value(w, mapping[h])


def _read_hash_map(r, read_value) -> dict:
    return {r.read(32): read_value(r) for _ in range(serde.read_varint(r))}


def _enc_trusted(td) -> bytes:
    w = io.BytesIO()
    w.write(td.pruning_point)
    w.write(serde.encode_hash_list(td.past_pruning_points))
    serde.write_varint(w, len(td.headers))
    for hdr in td.headers:
        serde.write_bytes(w, serde.encode_header(hdr))
    _write_hash_map(w, td.ghostdag, lambda w, gd: serde.write_bytes(w, serde.encode_ghostdag(gd)))
    _write_hash_map(w, td.statuses, lambda w, s: serde.write_bytes(w, s.encode()))
    _write_hash_map(w, td.reach_mergesets, lambda w, hs: w.write(serde.encode_hash_list(hs)))
    _write_hash_map(w, td.bodies, lambda w, txs: serde.write_bytes(w, serde.encode_txs(txs)))
    _write_hash_map(w, td.daa_excluded, lambda w, hs: w.write(serde.encode_hash_list(sorted(hs))))
    _write_hash_map(w, td.depth, lambda w, v: (w.write(v[0]), w.write(v[1])))
    _write_hash_map(w, td.pruning_samples, lambda w, s: w.write(s))
    serde.write_varint(w, len(td.pp_windows))
    for wt in sorted(td.pp_windows):
        serde.write_bytes(w, wt.encode())
        win = td.pp_windows[wt]
        serde.write_varint(w, len(win))
        for work, h in win:
            serde.write_varint(w, work)
            w.write(h)
    return w.getvalue()


def _dec_trusted(data: bytes):
    from kaspa_tpu.consensus.processes.pruning_proof import TrustedData

    r = io.BytesIO(data)
    td = TrustedData(pruning_point=r.read(32), past_pruning_points=serde.read_hash_list(r))
    td.headers = [serde.decode_header(serde.read_bytes(r)) for _ in range(serde.read_varint(r))]
    td.ghostdag = _read_hash_map(r, lambda r: serde.decode_ghostdag(serde.read_bytes(r)))
    td.statuses = _read_hash_map(r, lambda r: serde.read_bytes(r).decode())
    td.reach_mergesets = _read_hash_map(r, serde.read_hash_list)
    td.bodies = _read_hash_map(r, lambda r: serde.decode_txs(serde.read_bytes(r)))
    td.daa_excluded = _read_hash_map(r, lambda r: set(serde.read_hash_list(r)))
    td.depth = _read_hash_map(r, lambda r: (r.read(32), r.read(32)))
    td.pruning_samples = _read_hash_map(r, lambda r: r.read(32))
    td.pp_windows = {
        serde.read_bytes(r).decode(): [
            (serde.read_varint(r), r.read(32)) for _ in range(serde.read_varint(r))
        ]
        for _ in range(serde.read_varint(r))
    }
    return td


def _enc_utxo_chunk(p) -> bytes:
    w = io.BytesIO()
    serde.write_varint(w, p["offset"])
    serde.write_varint(w, len(p["pairs"]))
    for op, entry in p["pairs"]:
        w.write(serde.encode_outpoint(op))
        serde.write_bytes(w, serde.encode_utxo_entry(entry))
    w.write(b"\x01" if p["done"] else b"\x00")
    return w.getvalue()


def _dec_utxo_chunk(data: bytes) -> dict:
    r = io.BytesIO(data)
    offset = serde.read_varint(r)
    pairs = [
        (serde.decode_outpoint(r.read(36)), serde.decode_utxo_entry(serde.read_bytes(r)))
        for _ in range(serde.read_varint(r))
    ]
    return {"offset": offset, "pairs": pairs, "done": r.read(1) == b"\x01"}


def _enc_smt_request(p) -> bytes:
    """{pp: hash32, offset} — the pinned pruning point + paging offset."""
    w = io.BytesIO()
    w.write(p["pp"])
    serde.write_varint(w, p["offset"])
    return w.getvalue()


def _dec_smt_request(data: bytes) -> dict:
    r = io.BytesIO(data)
    return {"pp": _read_exact(r, 32), "offset": serde.read_varint(r)}


def _enc_smt_chunk(p) -> bytes:
    """KIP-21 lane-state chunk: metadata (first chunk only) + lane triples
    + shortcut-anchor segment entries (flows/src/ibd/streams.rs SmtStream)."""
    w = io.BytesIO()
    w.write(b"\x01" if p.get("active", True) else b"\x00")
    meta = p.get("meta")
    if meta is None:
        w.write(b"\x00")
    else:
        w.write(b"\x01")
        w.write(meta["lanes_root"] + meta["pcd"] + meta["parent_seq_commit"])
        w.write(meta["shortcut_block"] + meta["inactivity_shortcut"])
    serde.write_varint(w, p["offset"])
    serde.write_varint(w, len(p["lanes"]))
    for lk, tip, bs in p["lanes"]:
        w.write(lk + tip + struct.pack("<Q", bs))
    serde.write_varint(w, len(p["segment"]))
    for hdr in p["segment"]:
        serde.write_bytes(w, serde.encode_header(hdr))
    w.write(b"\x01" if p["done"] else b"\x00")
    return w.getvalue()


def _dec_smt_chunk(data: bytes) -> dict:
    r = io.BytesIO(data)
    active = _read_exact(r, 1) == b"\x01"
    meta = None
    if _read_exact(r, 1) == b"\x01":
        lanes_root, pcd, parent = _read_exact(r, 32), _read_exact(r, 32), _read_exact(r, 32)
        shortcut, inactivity = _read_exact(r, 32), _read_exact(r, 32)
        meta = {
            "lanes_root": lanes_root, "pcd": pcd, "parent_seq_commit": parent,
            "shortcut_block": shortcut, "inactivity_shortcut": inactivity,
        }
    offset = serde.read_varint(r)
    lanes = []
    for _ in range(serde.read_varint(r)):
        lk, tip = _read_exact(r, 32), _read_exact(r, 32)
        (bs,) = struct.unpack("<Q", _read_exact(r, 8))
        lanes.append((lk, tip, bs))
    segment = [
        serde.decode_header(serde.read_bytes(r)) for _ in range(serde.read_varint(r))
    ]
    return {
        "active": active, "meta": meta, "offset": offset,
        "lanes": lanes, "segment": segment, "done": _read_exact(r, 1) == b"\x01",
    }


def _enc_headers_chunk(p) -> bytes:
    """Headers-first chunk: header list + done flag + continuation."""
    w = io.BytesIO()
    serde.write_varint(w, len(p["headers"]))
    for h in p["headers"]:
        serde.write_bytes(w, serde.encode_header(h))
    w.write(b"\x01" if p["done"] else b"\x00")
    w.write(p["continuation"])
    return w.getvalue()


def _dec_headers_chunk(data: bytes) -> dict:
    r = io.BytesIO(data)
    headers = [serde.decode_header(serde.read_bytes(r)) for _ in range(serde.read_varint(r))]
    tail = r.read(33)
    if len(tail) != 33:
        raise WireError("truncated headers chunk (missing done/continuation)")
    return {"headers": headers, "done": tail[:1] == b"\x01", "continuation": tail[1:]}


def _enc_bodies(items) -> bytes:
    """[(block_hash, [tx, ...])] — v8 body-only sync payload."""
    w = io.BytesIO()
    serde.write_varint(w, len(items))
    for h, txs in items:
        w.write(h)
        serde.write_varint(w, len(txs))
        for tx in txs:
            serde.write_bytes(w, serde.encode_tx(tx))
    return w.getvalue()


def _dec_bodies(data: bytes) -> list:
    r = io.BytesIO(data)
    out = []
    for _ in range(serde.read_varint(r)):
        h = _read_exact(r, 32)
        txs = [serde.decode_tx(serde.read_bytes(r)) for _ in range(serde.read_varint(r))]
        out.append((h, txs))
    return out


def _enc_strings(items) -> bytes:
    w = io.BytesIO()
    serde.write_varint(w, len(items))
    for it in items:
        serde.write_bytes(w, it.encode())
    return w.getvalue()


def _dec_strings(data: bytes) -> list[str]:
    r = io.BytesIO(data)
    return [serde.read_bytes(r).decode() for _ in range(serde.read_varint(r))]


_CODECS = {
    MSG_VERSION: (_enc_version, _dec_version),
    MSG_VERACK: (_enc_varint, _dec_varint),
    MSG_INV_BLOCK: (lambda h: h, lambda d: d),  # single 32-byte hash
    MSG_REQUEST_BLOCK: (serde.encode_hash_list, serde.decode_hash_list_bytes),
    MSG_BLOCK: (serde.encode_block, serde.decode_block),
    MSG_INV_TXS: (serde.encode_hash_list, serde.decode_hash_list_bytes),
    MSG_REQUEST_TXS: (serde.encode_hash_list, serde.decode_hash_list_bytes),
    MSG_TX: (serde.encode_tx, serde.decode_tx),
    MSG_IBD_BLOCKS: (_enc_ibd_chunk, _dec_ibd_chunk),
    MSG_PING: (_enc_varint, _dec_varint),
    MSG_PONG: (_enc_varint, _dec_varint),
    MSG_REQUEST_IBD_CHAIN_INFO: (_enc_empty, _dec_empty),
    MSG_IBD_CHAIN_INFO: (_enc_chain_info, _dec_chain_info),
    MSG_REQUEST_PRUNING_PROOF: (_enc_empty, _dec_empty),
    MSG_PRUNING_PROOF: (_enc_proof, _dec_proof),
    MSG_REQUEST_TRUSTED_DATA: (_enc_empty, _dec_empty),
    MSG_TRUSTED_DATA: (_enc_trusted, _dec_trusted),
    MSG_REQUEST_PP_UTXOS: (_enc_varint, _dec_varint),
    MSG_PP_UTXO_CHUNK: (_enc_utxo_chunk, _dec_utxo_chunk),
    MSG_IBD_BLOCK_LOCATOR: (serde.encode_hash_list, serde.decode_hash_list_bytes),
    MSG_REQUEST_ANTIPAST: (lambda h: h, lambda d: d),  # single 32-byte hash
    MSG_REQUEST_ADDRESSES: (_enc_empty, _dec_empty),
    MSG_ADDRESSES: (_enc_strings, _dec_strings),
    MSG_REQUEST_PP_SMT: (_enc_smt_request, _dec_smt_request),
    MSG_PP_SMT_CHUNK: (_enc_smt_chunk, _dec_smt_chunk),
    MSG_REQUEST_BLOCK_BODIES: (serde.encode_hash_list, serde.decode_hash_list_bytes),
    MSG_BLOCK_BODIES: (_enc_bodies, _dec_bodies),
    MSG_REQUEST_HEADERS: (lambda h: h, lambda d: d),  # single 32-byte hash
    MSG_HEADERS: (_enc_headers_chunk, _dec_headers_chunk),
    MSG_REJECT: (lambda s_: s_.encode(), lambda d: d.decode("utf-8", "replace")),
}


def encode_frame(msg_type: str, payload) -> bytes:
    enc, _ = _CODECS[msg_type]
    body = enc(payload)
    return MAGIC + bytes([_TYPE_IDS[msg_type]]) + struct.pack("<I", len(body)) + body


def decode_frame(header: bytes) -> tuple[int, int]:
    """7-byte frame header -> (type_id, payload_len)."""
    if header[:2] != MAGIC:
        raise WireError("bad magic")
    type_id = header[2]
    if type_id not in _TYPE_NAMES:
        raise WireError(f"unknown message type {type_id}")
    (plen,) = struct.unpack("<I", header[3:7])
    if plen > MAX_FRAME:
        raise WireError(f"oversized frame {plen}")
    return type_id, plen


def decode_payload(type_id: int, body: bytes):
    name = _TYPE_NAMES[type_id]
    _, dec = _CODECS[name]
    return name, dec(body)


def read_message(read_exactly) -> tuple[str, object]:
    """Read one framed message via a `read_exactly(n) -> bytes` callable."""
    type_id, plen = decode_frame(read_exactly(7))
    return decode_payload(type_id, read_exactly(plen))
