"""P2P wire codec: binary frames for every flow message.

The role of `protocol/p2p/proto/{p2p,messages}.proto` + tonic framing in the
reference, over the framework's canonical binary codec (consensus/serde.py)
instead of protobuf.  Frame layout:

    magic(2) | type(1) | payload_len(4, LE) | payload

Payloads are serde-encoded.  The codec is pure (bytes in/out) so the flow
layer and tests use it without sockets; transport.py does the socket IO.
"""

from __future__ import annotations

import io
import struct

from kaspa_tpu.consensus import serde
from kaspa_tpu.p2p.node import (
    MSG_BLOCK,
    MSG_IBD_BLOCKS,
    MSG_INV_BLOCK,
    MSG_INV_TXS,
    MSG_REQUEST_BLOCK,
    MSG_REQUEST_IBD_BLOCKS,
    MSG_REQUEST_TXS,
    MSG_TX,
    MSG_VERACK,
    MSG_VERSION,
)

MAGIC = b"\x4b\x54"  # "KT"
MAX_FRAME = 1 << 30

MSG_PING = "ping"
MSG_PONG = "pong"

# wire ids (stable protocol surface; gaps reserved for IBD messages)
_TYPE_IDS = {
    MSG_VERSION: 0,
    MSG_VERACK: 1,
    MSG_INV_BLOCK: 2,
    MSG_REQUEST_BLOCK: 3,
    MSG_BLOCK: 4,
    MSG_INV_TXS: 5,
    MSG_REQUEST_TXS: 6,
    MSG_TX: 7,
    MSG_REQUEST_IBD_BLOCKS: 8,
    MSG_IBD_BLOCKS: 9,
    MSG_PING: 10,
    MSG_PONG: 11,
}
_TYPE_NAMES = {v: k for k, v in _TYPE_IDS.items()}


def _enc_version(p) -> bytes:
    """payload: {protocol_version, network, listen_port}"""
    w = io.BytesIO()
    serde.write_varint(w, p["protocol_version"])
    serde.write_bytes(w, p["network"].encode())
    serde.write_varint(w, p.get("listen_port", 0))
    return w.getvalue()


def _dec_version(data: bytes):
    r = io.BytesIO(data)
    return {
        "protocol_version": serde.read_varint(r),
        "network": serde.read_bytes(r).decode(),
        "listen_port": serde.read_varint(r),
    }


def _enc_varint(v: int) -> bytes:
    w = io.BytesIO()
    serde.write_varint(w, v)
    return w.getvalue()


def _dec_varint(data: bytes) -> int:
    return serde.read_varint(io.BytesIO(data))


def _enc_blocks(blocks) -> bytes:
    w = io.BytesIO()
    serde.write_varint(w, len(blocks))
    for b in blocks:
        serde.write_bytes(w, serde.encode_block(b))
    return w.getvalue()


def _dec_blocks(data: bytes):
    r = io.BytesIO(data)
    return [serde.decode_block(serde.read_bytes(r)) for _ in range(serde.read_varint(r))]


_CODECS = {
    MSG_VERSION: (_enc_version, _dec_version),
    MSG_VERACK: (_enc_varint, _dec_varint),
    MSG_INV_BLOCK: (lambda h: h, lambda d: d),  # single 32-byte hash
    MSG_REQUEST_BLOCK: (serde.encode_hash_list, serde.decode_hash_list_bytes),
    MSG_BLOCK: (serde.encode_block, serde.decode_block),
    MSG_INV_TXS: (serde.encode_hash_list, serde.decode_hash_list_bytes),
    MSG_REQUEST_TXS: (serde.encode_hash_list, serde.decode_hash_list_bytes),
    MSG_TX: (serde.encode_tx, serde.decode_tx),
    MSG_REQUEST_IBD_BLOCKS: (serde.encode_hash_list, serde.decode_hash_list_bytes),
    MSG_IBD_BLOCKS: (_enc_blocks, _dec_blocks),
    MSG_PING: (_enc_varint, _dec_varint),
    MSG_PONG: (_enc_varint, _dec_varint),
}


class WireError(Exception):
    pass


def encode_frame(msg_type: str, payload) -> bytes:
    enc, _ = _CODECS[msg_type]
    body = enc(payload)
    return MAGIC + bytes([_TYPE_IDS[msg_type]]) + struct.pack("<I", len(body)) + body


def decode_frame(header: bytes) -> tuple[int, int]:
    """7-byte frame header -> (type_id, payload_len)."""
    if header[:2] != MAGIC:
        raise WireError("bad magic")
    type_id = header[2]
    if type_id not in _TYPE_NAMES:
        raise WireError(f"unknown message type {type_id}")
    (plen,) = struct.unpack("<I", header[3:7])
    if plen > MAX_FRAME:
        raise WireError(f"oversized frame {plen}")
    return type_id, plen


def decode_payload(type_id: int, body: bytes):
    name = _TYPE_NAMES[type_id]
    _, dec = _CODECS[name]
    return name, dec(body)


def read_message(read_exactly) -> tuple[str, object]:
    """Read one framed message via a `read_exactly(n) -> bytes` callable."""
    type_id, plen = decode_frame(read_exactly(7))
    return decode_payload(type_id, read_exactly(plen))
