"""rothschild: the transaction load generator.

Reference: rothschild/src/main.rs — a self-spending tx spammer for load
testing: derives a keypair, tracks its UTXOs via the node, and submits
transactions at a target TPS, maintaining enough UTXO fan-out to sustain
the rate (recommended <= 50-100 TPS per node, docs/testnet10-transition.md:69).

Run against a live daemon wire:
    python -m kaspa_tpu.tools.rothschild --rpcserver 127.0.0.1:16110 \
        --seed <hex> --tps 20 --duration 30

The same engine drives in-process for tests (Rothschild.run_against).
"""

from __future__ import annotations

import argparse
import time

from kaspa_tpu.consensus import hashing as chash
from kaspa_tpu.consensus.model import (
    SUBNETWORK_ID_NATIVE,
    ComputeCommit,
    Transaction,
    TransactionInput,
    TransactionOutpoint,
    TransactionOutput,
    UtxoEntry,
)
from kaspa_tpu.consensus.mass import MassCalculator
from kaspa_tpu.crypto import eclib
from kaspa_tpu.txscript import standard
from kaspa_tpu.wallet.account import Account


class Rothschild:
    """Tx spammer engine: split-then-spam.

    Keeps a local view of its own spendable outpoints (seeded from the
    node, extended by its own tx outputs) so it can chain spends without
    waiting for confirmations — the reference tracks pending outpoints
    the same way."""

    def __init__(self, account: Account, mass_calculator: MassCalculator | None = None, fee: int = 5000):
        self.account = account
        self.spk = account.receive_keys[0].spk
        self.key = account.receive_keys[0].key.key
        self.mc = mass_calculator if mass_calculator is not None else MassCalculator()
        self.fee = fee
        self.available: list = []  # (outpoint, amount)
        self.stats = {"submitted": 0, "rejected": 0}

    def seed_utxos(self, utxos) -> None:
        """[(outpoint, UtxoEntry)] — mature spendables owned by our key."""
        self.available = [(op, e.amount) for op, e in utxos]
        self.available.sort(key=lambda t: -t[1])

    def _build_self_spend(self, fan_out: int = 2) -> Transaction | None:
        """Spend one outpoint into `fan_out` outputs back to ourselves."""
        while self.available:
            op, amount = self.available.pop()
            if amount > self.fee + fan_out:
                break
        else:
            return None
        per_out = (amount - self.fee) // fan_out
        outs = [TransactionOutput(per_out, self.spk) for _ in range(fan_out - 1)]
        outs.append(TransactionOutput(amount - self.fee - per_out * (fan_out - 1), self.spk))
        tx = Transaction(
            0,
            [TransactionInput(op, b"", 0, ComputeCommit.sigops(1))],
            outs,
            0,
            SUBNETWORK_ID_NATIVE,
            0,
            b"",
        )
        entry = UtxoEntry(amount, self.spk, 0, False)
        tx.storage_mass = self.mc.calc_contextual_masses(tx, [entry]) or 0
        msg = chash.calc_schnorr_signature_hash(tx, [entry], 0, chash.SIG_HASH_ALL, chash.SigHashReusedValues())
        sig = eclib.schnorr_sign(msg, self.key, b"\x00" * 32)
        tx.inputs[0].signature_script = standard.schnorr_signature_script(sig, chash.SIG_HASH_ALL)
        tx._id_cache = None
        # our own outputs become immediately spendable (mempool chaining)
        for i, out in enumerate(tx.outputs):
            self.available.insert(0, (TransactionOutpoint(tx.id(), i), out.value))
        return tx

    def run_against(self, submit, tps: float, duration: float, clock=time.monotonic, sleep=time.sleep) -> dict:
        """Pump txs through `submit(tx) -> None | raise` at the target rate."""
        interval = 1.0 / tps if tps > 0 else 0.0
        deadline = clock() + duration
        next_fire = clock()
        while clock() < deadline:
            tx = self._build_self_spend()
            if tx is None:
                break  # fan-out exhausted
            try:
                submit(tx)
                self.stats["submitted"] += 1
            except Exception:
                self.stats["rejected"] += 1
            next_fire += interval
            delay = next_fire - clock()
            if delay > 0:
                sleep(delay)
        return dict(self.stats)


def main(argv=None) -> None:
    from kaspa_tpu.node.daemon import rpc_call
    from kaspa_tpu.wallet.__main__ import tx_to_wire

    p = argparse.ArgumentParser(prog="rothschild", description="kaspa-tpu tx load generator")
    p.add_argument("--rpcserver", default="127.0.0.1:16110")
    p.add_argument("--seed", required=True, help="hex seed for the spam wallet")
    p.add_argument("--tps", type=float, default=10.0)
    p.add_argument("--duration", type=float, default=60.0)
    p.add_argument("--prefix", default="kaspasim")
    args = p.parse_args(argv)

    account = Account.from_seed(bytes.fromhex(args.seed), prefix=args.prefix)
    addr = account.addresses()[0]
    spam = Rothschild(account)
    utxos = rpc_call(args.rpcserver, "getUtxosByAddresses", {"addresses": [addr]})
    spk = account.receive_keys[0].spk
    spam.seed_utxos(
        (
            TransactionOutpoint(bytes.fromhex(u["outpoint"]["transaction_id"]), u["outpoint"]["index"]),
            UtxoEntry(
                u["utxo_entry"]["amount"], spk, u["utxo_entry"]["block_daa_score"], u["utxo_entry"]["is_coinbase"]
            ),
        )
        for u in utxos
    )
    print(f"rothschild: {len(spam.available)} spendable outpoints on {addr}")

    def submit(tx):
        rpc_call(args.rpcserver, "submitTransaction", {"tx": tx_to_wire(tx)})

    stats = spam.run_against(submit, args.tps, args.duration)
    print(f"rothschild: {stats}")


if __name__ == "__main__":
    main()
