"""Borsh wRPC encoding: the binary counterpart of the JSON WebSocket RPC.

Payload layouts are byte-exact ports of the reference's versioned
`Serializer` impls over borsh primitives (rpc/core/src/model/message.rs,
block.rs, header.rs, tx.rs — each codec cites its source): little-endian
fixed-width ints, `bool` as one byte, `Vec`/`String` with a u32 length,
`Option` with a one-byte tag, `Hash` as 32 raw bytes, `SubnetworkId` as 20
raw bytes, `Uint192` blue work as 24 bytes LE
(math/src/lib.rs construct_uint!(Uint192, 3)).

The outer frame is NOT the reference's: its wRPC rides the external
workflow-rpc crate whose Borsh framing is not vendored here, so this module
defines an explicit documented frame instead:

    kind(u8: 0=request 1=response 2=notification 3=error)
    | id(u64 LE; requests/responses only)
    | op(u32 LE, RpcApiOps discriminants from rpc/core/src/api/ops.rs)
    | payload (reference-exact message encoding)

Ops used: Subscribe=3, SubmitBlock=117, GetInfo=141,
BlockAddedNotification=60 (ops.rs:28,74,122,48).
"""

from __future__ import annotations

import io
import struct

# --- RpcApiOps discriminants (rpc/core/src/api/ops.rs) ---
OP_SUBSCRIBE = 3
OP_BLOCK_ADDED_NOTIFICATION = 60
OP_SUBMIT_BLOCK = 117
OP_GET_INFO = 141

KIND_REQUEST = 0
KIND_RESPONSE = 1
KIND_NOTIFICATION = 2
KIND_ERROR = 3


# ---------------------------------------------------------------------------
# borsh primitives
# ---------------------------------------------------------------------------

def w_u8(w, v):
    w.write(struct.pack("<B", v))


def w_u16(w, v):
    w.write(struct.pack("<H", v))


def w_u32(w, v):
    w.write(struct.pack("<I", v))


def w_u64(w, v):
    w.write(struct.pack("<Q", v))


def w_f64(w, v):
    w.write(struct.pack("<d", v))


def w_bool(w, v):
    w.write(b"\x01" if v else b"\x00")


def w_bytes(w, b):
    w_u32(w, len(b))
    w.write(b)


def w_string(w, s):
    w_bytes(w, s.encode("utf-8"))


def w_hash(w, h):
    assert len(h) == 32
    w.write(h)


def w_uint192(w, v):
    w.write(v.to_bytes(24, "little"))


def _rd(r, n):
    b = r.read(n)
    if len(b) != n:
        raise EOFError(f"truncated borsh read: wanted {n}, got {len(b)}")
    return b


def r_u8(r):
    return struct.unpack("<B", _rd(r, 1))[0]


def r_u16(r):
    return struct.unpack("<H", _rd(r, 2))[0]


def r_u32(r):
    return struct.unpack("<I", _rd(r, 4))[0]


def r_u64(r):
    return struct.unpack("<Q", _rd(r, 8))[0]


def r_f64(r):
    return struct.unpack("<d", _rd(r, 8))[0]


def r_bool(r):
    return _rd(r, 1) == b"\x01"


def r_bytes(r):
    return _rd(r, r_u32(r))


def r_string(r):
    return r_bytes(r).decode("utf-8")


def r_hash(r):
    return _rd(r, 32)


def r_uint192(r):
    return int.from_bytes(_rd(r, 24), "little")


# ---------------------------------------------------------------------------
# message payload codecs (reference-exact)
# ---------------------------------------------------------------------------

def encode_get_info_request(w) -> None:
    """message.rs:250-254."""
    w_u16(w, 1)


def decode_get_info_request(r) -> dict:
    r_u16(r)
    return {}


def encode_get_info_response(w, info: dict) -> None:
    """message.rs:276-286: struct version + 2 strings, u64, 4 bools."""
    w_u16(w, 1)
    w_string(w, info["p2p_id"])
    w_u64(w, info["mempool_size"])
    w_string(w, info["server_version"])
    w_bool(w, info["is_utxo_indexed"])
    w_bool(w, info["is_synced"])
    w_bool(w, info["has_notify_command"])
    w_bool(w, info["has_message_id"])


def decode_get_info_response(r) -> dict:
    r_u16(r)
    return {
        "p2p_id": r_string(r),
        "mempool_size": r_u64(r),
        "server_version": r_string(r),
        "is_utxo_indexed": r_bool(r),
        "is_synced": r_bool(r),
        "has_notify_command": r_bool(r),
        "has_message_id": r_bool(r),
    }


def encode_outpoint(w, op) -> None:
    """tx.rs:128-135: u8 version, TransactionId hash, u32 index."""
    w_u8(w, 1)
    w_hash(w, op.transaction_id)
    w_u32(w, op.index)


def decode_outpoint(r):
    from kaspa_tpu.consensus.model import TransactionOutpoint

    r_u8(r)
    return TransactionOutpoint(r_hash(r), r_u32(r))


def encode_tx_input(w, inp) -> None:
    """tx.rs:194-205 (struct version 2 carries the compute budget)."""
    w_u8(w, 2)
    encode_outpoint(w, inp.previous_outpoint)
    w_bytes(w, inp.signature_script)
    w_u64(w, inp.sequence)
    cc = inp.compute_commit
    w_u8(w, cc.value if cc.kind == "sigops" else 0)  # sig_op_count
    w_u8(w, 0)  # Option<RpcTransactionInputVerboseData>: None
    w_u16(w, cc.value if cc.kind == "budget" else 0)  # compute_budget


def decode_tx_input(r, tx_version: int = 0):
    from kaspa_tpu.consensus.model import ComputeCommit, TransactionInput

    version = r_u8(r)
    op = decode_outpoint(r)
    script = r_bytes(r)
    seq = r_u64(r)
    sig_ops = r_u8(r)
    if r_u8(r) == 1:  # verbose data present: struct is empty + u8 version
        r_u8(r)
    budget = r_u16(r) if version > 1 else 0
    # the TRANSACTION version selects the commit variant (model/tx.py:64,
    # mirroring the reference's versioned sighash field selection) — a
    # nonzero-budget heuristic would flip budget(0) into sigops(0)
    if ComputeCommit.version_expects_compute_budget_field(tx_version):
        cc = ComputeCommit.budget(budget)
    else:
        cc = ComputeCommit.sigops(sig_ops)
    return TransactionInput(op, script, seq, cc)


def encode_tx_output(w, out) -> None:
    """tx.rs:268-276 (struct version 2 carries the covenant binding)."""
    w_u8(w, 2)
    w_u64(w, out.value)
    w_u16(w, out.script_public_key.version)  # RpcScriptPublicKey borsh:
    w_bytes(w, out.script_public_key.script)  # u16 version + Vec<u8> script
    w_u8(w, 0)  # Option<RpcTransactionOutputVerboseData>: None
    cov = out.covenant
    if cov is None:
        w_u8(w, 0)
    else:
        w_u8(w, 1)
        w_u8(w, 1)  # RpcCovenantBinding struct version (tx.rs:319-325)
        w_u16(w, cov.authorizing_input)
        w_hash(w, cov.covenant_id)


def decode_tx_output(r):
    from kaspa_tpu.consensus.model import Covenant, ScriptPublicKey, TransactionOutput

    version = r_u8(r)
    value = r_u64(r)
    spk = ScriptPublicKey(r_u16(r), r_bytes(r))
    if r_u8(r) == 1:  # verbose data: skip (version u8 + script class str + addr str)
        r_u8(r)
        r_string(r)
        r_string(r)
    cov = None
    if version > 1 and r_u8(r) == 1:
        r_u8(r)
        cov = Covenant(r_u16(r), r_hash(r))
    return TransactionOutput(value, spk, cov)


def encode_tx(w, tx) -> None:
    """tx.rs:478-493."""
    w_u16(w, 1)
    w_u16(w, tx.version)
    w_u32(w, len(tx.inputs))
    for inp in tx.inputs:
        encode_tx_input(w, inp)
    w_u32(w, len(tx.outputs))
    for out in tx.outputs:
        encode_tx_output(w, out)
    w_u64(w, tx.lock_time)
    w.write(tx.subnetwork_id)  # RpcSubnetworkId: 20 raw bytes
    w_u64(w, tx.gas)
    w_bytes(w, tx.payload)
    w_u64(w, tx.storage_mass)
    w_u8(w, 0)  # Option<RpcTransactionVerboseData>: None


def decode_tx(r):
    from kaspa_tpu.consensus.model import Transaction

    r_u16(r)
    version = r_u16(r)
    inputs = [decode_tx_input(r, version) for _ in range(r_u32(r))]
    outputs = [decode_tx_output(r) for _ in range(r_u32(r))]
    lock_time = r_u64(r)
    subnetwork = _rd(r, 20)
    gas = r_u64(r)
    payload = r_bytes(r)
    storage_mass = r_u64(r)
    if r_u8(r) == 1:  # verbose data: u8 version + txid hash + u64 compute mass
        r_u8(r)
        r_hash(r)
        r_u64(r)
    return Transaction(version, inputs, outputs, lock_time, subnetwork, gas, payload, storage_mass)


def _encode_header_fields(w, h) -> None:
    w_u16(w, h.version)
    w_u32(w, len(h.parents_by_level))
    for level in h.parents_by_level:
        w_u32(w, len(level))
        for p in level:
            w_hash(w, p)
    w_hash(w, h.hash_merkle_root)
    w_hash(w, h.accepted_id_merkle_root)
    w_hash(w, h.utxo_commitment)
    w_u64(w, h.timestamp)
    w_u32(w, h.bits)
    w_u64(w, h.nonce)
    w_u64(w, h.daa_score)
    w_uint192(w, h.blue_work)
    w_u64(w, h.blue_score)
    w_hash(w, h.pruning_point)


def _decode_header_fields(r) -> dict:
    version = r_u16(r)
    parents = []
    for _ in range(r_u32(r)):
        parents.append([r_hash(r) for _ in range(r_u32(r))])
    return {
        "version": version,
        "parents_by_level": parents,
        "hash_merkle_root": r_hash(r),
        "accepted_id_merkle_root": r_hash(r),
        "utxo_commitment": r_hash(r),
        "timestamp": r_u64(r),
        "bits": r_u32(r),
        "nonce": r_u64(r),
        "daa_score": r_u64(r),
        "blue_work": r_uint192(r),
        "blue_score": r_u64(r),
        "pruning_point": r_hash(r),
    }


def encode_raw_header(w, h) -> None:
    """header.rs:286-305 (RpcRawHeader: no hash field)."""
    w_u16(w, 1)
    _encode_header_fields(w, h)


def decode_raw_header(r):
    from kaspa_tpu.consensus.model import Header

    r_u16(r)
    f = _decode_header_fields(r)
    return Header(**f)


def encode_header(w, h) -> None:
    """header.rs:148-167 (RpcHeader: leads with the block hash)."""
    w_u16(w, 1)
    w_hash(w, h.hash)
    _encode_header_fields(w, h)


def encode_submit_block_request(w, block, allow_non_daa_blocks: bool = False) -> None:
    """message.rs:34-41: struct version + RpcRawBlock + bool."""
    w_u16(w, 1)
    w_u16(w, 1)  # RpcRawBlock struct version (block.rs:45-52)
    encode_raw_header(w, block.header)
    w_u32(w, len(block.transactions))
    for tx in block.transactions:
        encode_tx(w, tx)
    w_bool(w, allow_non_daa_blocks)


def decode_submit_block_request(r):
    from kaspa_tpu.consensus.model.block import Block

    r_u16(r)
    r_u16(r)  # raw block struct version
    header = decode_raw_header(r)
    txs = [decode_tx(r) for _ in range(r_u32(r))]
    allow_non_daa = r_bool(r)
    return Block(header, txs), allow_non_daa


# SubmitBlockRejectReason discriminants (message.rs:54-60, use_discriminant)
REJECT_BLOCK_INVALID = 1
REJECT_IS_IN_IBD = 2
REJECT_ROUTE_IS_FULL = 3


def encode_submit_block_response(w, reject_reason: int | None) -> None:
    """message.rs:98-103; SubmitBlockReport borsh enum: 0=Success,
    1=Reject(reason) (message.rs:82-85)."""
    w_u16(w, 1)
    if reject_reason is None:
        w_u8(w, 0)
    else:
        w_u8(w, 1)
        w_u8(w, reject_reason)


def decode_submit_block_response(r) -> int | None:
    r_u16(r)
    if r_u8(r) == 0:
        return None
    return r_u8(r)


def encode_block_added_notification(w, block, verbose: dict) -> None:
    """message.rs:2991-2996 wrapping RpcBlock (block.rs:23-31) with its
    verbose data (block.rs:80-92)."""
    w_u16(w, 1)
    w_u16(w, 1)  # RpcBlock struct version
    encode_header(w, block.header)
    w_u32(w, len(block.transactions))
    for tx in block.transactions:
        encode_tx(w, tx)
    w_u8(w, 1)  # Option<RpcBlockVerboseData>: Some
    w_u8(w, 1)  # verbose struct version
    w_hash(w, block.hash)
    w_f64(w, verbose.get("difficulty", 0.0))
    w_hash(w, verbose.get("selected_parent_hash", bytes(32)))
    ids = [tx.id() for tx in block.transactions]
    w_u32(w, len(ids))
    for i in ids:
        w_hash(w, i)
    w_bool(w, verbose.get("is_header_only", False))
    w_u64(w, verbose.get("blue_score", block.header.blue_score))
    for key in ("children_hashes", "merge_set_blues_hashes", "merge_set_reds_hashes"):
        hs = verbose.get(key, [])
        w_u32(w, len(hs))
        for h in hs:
            w_hash(w, h)
    w_bool(w, verbose.get("is_chain_block", False))


# ---------------------------------------------------------------------------
# framing + dispatch
# ---------------------------------------------------------------------------

def encode_frame(kind: int, op: int, payload: bytes, msg_id: int | None = None) -> bytes:
    w = io.BytesIO()
    w_u8(w, kind)
    if kind in (KIND_REQUEST, KIND_RESPONSE, KIND_ERROR):
        w_u64(w, msg_id or 0)
    w_u32(w, op)
    w.write(payload)
    return w.getvalue()


def decode_frame(data: bytes):
    r = io.BytesIO(data)
    kind = r_u8(r)
    msg_id = r_u64(r) if kind in (KIND_REQUEST, KIND_RESPONSE, KIND_ERROR) else None
    op = r_u32(r)
    return kind, msg_id, op, r


def handle_frame(daemon, data: bytes, notification_sink=None, listener_ref=None, stop=None) -> bytes:
    """Dispatch one Borsh wRPC request frame; returns the response frame.

    The server side of the reference's Borsh-encoding wRPC endpoint
    (rpc/wrpc/server/src/server.rs) over this module's documented frame.
    """
    msg_id = 0
    try:
        kind, msg_id, op, r = decode_frame(data)
        if kind != KIND_REQUEST:
            raise ValueError(f"unexpected frame kind {kind}")
        if op == OP_GET_INFO:
            decode_get_info_request(r)
            info = daemon.dispatch("getInfo", {})
            w = io.BytesIO()
            encode_get_info_response(w, info)
            return encode_frame(KIND_RESPONSE, op, w.getvalue(), msg_id)
        if op == OP_SUBMIT_BLOCK:
            from kaspa_tpu.consensus.consensus import RuleError
            from kaspa_tpu.core.log import get_logger

            block, _allow_non_daa = decode_submit_block_request(r)
            w = io.BytesIO()
            try:
                with daemon._dispatch_lock:
                    daemon.node.submit_block(block)
                encode_submit_block_response(w, None)
            except (RuleError, ValueError) as e:
                # consensus rejection: the typed reject report
                get_logger("wrpc.borsh").info("block %s rejected: %s", block.hash.hex()[:16], e)
                encode_submit_block_response(w, REJECT_BLOCK_INVALID)
            # internal failures propagate to the KIND_ERROR frame below —
            # a miner must not read a node bug as "your block was invalid"
            return encode_frame(KIND_RESPONSE, op, w.getvalue(), msg_id)
        if op == OP_SUBSCRIBE:
            event_op = r_u32(r)
            if event_op != OP_BLOCK_ADDED_NOTIFICATION:
                raise ValueError(f"unsupported subscription op {event_op}")
            # register a Borsh listener directly on the notifier: the raw
            # Notification carries the Block object, which this encoding
            # needs in full (the JSON path only streams a summary)
            with daemon._dispatch_lock:
                if listener_ref[0] is None:

                    def on_notification(n, _sink=notification_sink, _stop=stop):
                        if _stop is not None and _stop.is_set():
                            return
                        if n.event_type != "block-added":
                            return
                        blk = n.data["block"]
                        try:
                            # enqueue a thunk: the full-block encode runs on
                            # the connection's writer thread, never on the
                            # consensus thread publishing the event
                            _sink.put_nowait(lambda _b=blk: make_block_added_frame(_b))
                        except Exception:  # noqa: BLE001 - slow consumer: drop
                            pass

                    listener_ref[0] = daemon.rpc.register_listener(on_notification)
                daemon.rpc.start_notify(listener_ref[0], "block-added")
            return encode_frame(KIND_RESPONSE, op, b"", msg_id)
        raise ValueError(f"unsupported borsh op {op}")
    except Exception as e:  # noqa: BLE001 - wire boundary
        w = io.BytesIO()
        w_string(w, str(e))
        return encode_frame(KIND_ERROR, 0, w.getvalue(), msg_id or 0)


def make_block_added_frame(block, verbose: dict | None = None) -> bytes:
    w = io.BytesIO()
    encode_block_added_notification(w, block, verbose or {})
    return encode_frame(KIND_NOTIFICATION, OP_BLOCK_ADDED_NOTIFICATION, w.getvalue())
