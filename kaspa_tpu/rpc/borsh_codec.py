"""Borsh wRPC encoding: the binary counterpart of the JSON WebSocket RPC.

Payload layouts are byte-exact ports of the reference's versioned
`Serializer` impls over borsh primitives (rpc/core/src/model/message.rs,
block.rs, header.rs, tx.rs — each codec cites its source): little-endian
fixed-width ints, `bool` as one byte, `Vec`/`String` with a u32 length,
`Option` with a one-byte tag, `Hash` as 32 raw bytes, `SubnetworkId` as 20
raw bytes, `Uint192` blue work as 24 bytes LE
(math/src/lib.rs construct_uint!(Uint192, 3)).

The outer frame is NOT the reference's: its wRPC rides the external
workflow-rpc crate whose Borsh framing is not vendored here, so this module
defines an explicit documented frame instead:

    kind(u8: 0=request 1=response 2=notification 3=error)
    | id(u64 LE; requests/responses only)
    | op(u32 LE, RpcApiOps discriminants from rpc/core/src/api/ops.rs)
    | payload (reference-exact message encoding)

Ops used: Subscribe=3, SubmitBlock=117, GetInfo=141,
BlockAddedNotification=60 (ops.rs:28,74,122,48).
"""

from __future__ import annotations

import io
import struct

# --- RpcApiOps discriminants (rpc/core/src/api/ops.rs) ---
OP_SUBSCRIBE = 3
OP_BLOCK_ADDED_NOTIFICATION = 60
OP_SUBMIT_BLOCK = 117
OP_GET_INFO = 141
# serving-tier methods: this frame's op assignment (the reference numbers
# them inside the external workflow-rpc crate); pinned by the golden
# fixtures under tests/fixtures/borsh/
OP_GET_UTXOS_BY_ADDRESSES = 145
OP_GET_BALANCE_BY_ADDRESS = 146
OP_GET_COIN_SUPPLY = 147
# notification ops follow the EVENT_TYPES order from the block-added base:
# op = 60 + EVENT_TYPES.index(event) (ops.rs keeps notifications contiguous)
OP_UTXOS_CHANGED_NOTIFICATION = 64

KIND_REQUEST = 0
KIND_RESPONSE = 1
KIND_NOTIFICATION = 2
KIND_ERROR = 3


# ---------------------------------------------------------------------------
# borsh primitives
# ---------------------------------------------------------------------------

def w_u8(w, v):
    w.write(struct.pack("<B", v))


def w_u16(w, v):
    w.write(struct.pack("<H", v))


def w_u32(w, v):
    w.write(struct.pack("<I", v))


def w_u64(w, v):
    w.write(struct.pack("<Q", v))


def w_f64(w, v):
    w.write(struct.pack("<d", v))


def w_bool(w, v):
    w.write(b"\x01" if v else b"\x00")


def w_bytes(w, b):
    w_u32(w, len(b))
    w.write(b)


def w_string(w, s):
    w_bytes(w, s.encode("utf-8"))


def w_hash(w, h):
    assert len(h) == 32
    w.write(h)


def w_uint192(w, v):
    w.write(v.to_bytes(24, "little"))


def _rd(r, n):
    b = r.read(n)
    if len(b) != n:
        raise EOFError(f"truncated borsh read: wanted {n}, got {len(b)}")
    return b


def r_u8(r):
    return struct.unpack("<B", _rd(r, 1))[0]


def r_u16(r):
    return struct.unpack("<H", _rd(r, 2))[0]


def r_u32(r):
    return struct.unpack("<I", _rd(r, 4))[0]


def r_u64(r):
    return struct.unpack("<Q", _rd(r, 8))[0]


def r_f64(r):
    return struct.unpack("<d", _rd(r, 8))[0]


def r_bool(r):
    return _rd(r, 1) == b"\x01"


def r_bytes(r):
    return _rd(r, r_u32(r))


def r_string(r):
    return r_bytes(r).decode("utf-8")


def r_hash(r):
    return _rd(r, 32)


def r_uint192(r):
    return int.from_bytes(_rd(r, 24), "little")


# ---------------------------------------------------------------------------
# message payload codecs (reference-exact)
# ---------------------------------------------------------------------------

def encode_get_info_request(w) -> None:
    """message.rs:250-254."""
    w_u16(w, 1)


def decode_get_info_request(r) -> dict:
    r_u16(r)
    return {}


def encode_get_info_response(w, info: dict) -> None:
    """message.rs:276-286: struct version + 2 strings, u64, 4 bools."""
    w_u16(w, 1)
    w_string(w, info["p2p_id"])
    w_u64(w, info["mempool_size"])
    w_string(w, info["server_version"])
    w_bool(w, info["is_utxo_indexed"])
    w_bool(w, info["is_synced"])
    w_bool(w, info["has_notify_command"])
    w_bool(w, info["has_message_id"])


def decode_get_info_response(r) -> dict:
    r_u16(r)
    return {
        "p2p_id": r_string(r),
        "mempool_size": r_u64(r),
        "server_version": r_string(r),
        "is_utxo_indexed": r_bool(r),
        "is_synced": r_bool(r),
        "has_notify_command": r_bool(r),
        "has_message_id": r_bool(r),
    }


def encode_outpoint(w, op) -> None:
    """tx.rs:128-135: u8 version, TransactionId hash, u32 index."""
    w_u8(w, 1)
    w_hash(w, op.transaction_id)
    w_u32(w, op.index)


def decode_outpoint(r):
    from kaspa_tpu.consensus.model import TransactionOutpoint

    r_u8(r)
    return TransactionOutpoint(r_hash(r), r_u32(r))


def encode_tx_input(w, inp) -> None:
    """tx.rs:194-205 (struct version 2 carries the compute budget)."""
    w_u8(w, 2)
    encode_outpoint(w, inp.previous_outpoint)
    w_bytes(w, inp.signature_script)
    w_u64(w, inp.sequence)
    cc = inp.compute_commit
    w_u8(w, cc.value if cc.kind == "sigops" else 0)  # sig_op_count
    w_u8(w, 0)  # Option<RpcTransactionInputVerboseData>: None
    w_u16(w, cc.value if cc.kind == "budget" else 0)  # compute_budget


def decode_tx_input(r, tx_version: int = 0):
    from kaspa_tpu.consensus.model import ComputeCommit, TransactionInput

    version = r_u8(r)
    op = decode_outpoint(r)
    script = r_bytes(r)
    seq = r_u64(r)
    sig_ops = r_u8(r)
    if r_u8(r) == 1:  # verbose data present: struct is empty + u8 version
        r_u8(r)
    budget = r_u16(r) if version > 1 else 0
    # the TRANSACTION version selects the commit variant (model/tx.py:64,
    # mirroring the reference's versioned sighash field selection) — a
    # nonzero-budget heuristic would flip budget(0) into sigops(0)
    if ComputeCommit.version_expects_compute_budget_field(tx_version):
        cc = ComputeCommit.budget(budget)
    else:
        cc = ComputeCommit.sigops(sig_ops)
    return TransactionInput(op, script, seq, cc)


def encode_tx_output(w, out) -> None:
    """tx.rs:268-276 (struct version 2 carries the covenant binding)."""
    w_u8(w, 2)
    w_u64(w, out.value)
    w_u16(w, out.script_public_key.version)  # RpcScriptPublicKey borsh:
    w_bytes(w, out.script_public_key.script)  # u16 version + Vec<u8> script
    w_u8(w, 0)  # Option<RpcTransactionOutputVerboseData>: None
    cov = out.covenant
    if cov is None:
        w_u8(w, 0)
    else:
        w_u8(w, 1)
        w_u8(w, 1)  # RpcCovenantBinding struct version (tx.rs:319-325)
        w_u16(w, cov.authorizing_input)
        w_hash(w, cov.covenant_id)


def decode_tx_output(r):
    from kaspa_tpu.consensus.model import Covenant, ScriptPublicKey, TransactionOutput

    version = r_u8(r)
    value = r_u64(r)
    spk = ScriptPublicKey(r_u16(r), r_bytes(r))
    if r_u8(r) == 1:  # verbose data: skip (version u8 + script class str + addr str)
        r_u8(r)
        r_string(r)
        r_string(r)
    cov = None
    if version > 1 and r_u8(r) == 1:
        r_u8(r)
        cov = Covenant(r_u16(r), r_hash(r))
    return TransactionOutput(value, spk, cov)


def encode_tx(w, tx) -> None:
    """tx.rs:478-493."""
    w_u16(w, 1)
    w_u16(w, tx.version)
    w_u32(w, len(tx.inputs))
    for inp in tx.inputs:
        encode_tx_input(w, inp)
    w_u32(w, len(tx.outputs))
    for out in tx.outputs:
        encode_tx_output(w, out)
    w_u64(w, tx.lock_time)
    w.write(tx.subnetwork_id)  # RpcSubnetworkId: 20 raw bytes
    w_u64(w, tx.gas)
    w_bytes(w, tx.payload)
    w_u64(w, tx.storage_mass)
    w_u8(w, 0)  # Option<RpcTransactionVerboseData>: None


def decode_tx(r):
    from kaspa_tpu.consensus.model import Transaction

    r_u16(r)
    version = r_u16(r)
    inputs = [decode_tx_input(r, version) for _ in range(r_u32(r))]
    outputs = [decode_tx_output(r) for _ in range(r_u32(r))]
    lock_time = r_u64(r)
    subnetwork = _rd(r, 20)
    gas = r_u64(r)
    payload = r_bytes(r)
    storage_mass = r_u64(r)
    if r_u8(r) == 1:  # verbose data: u8 version + txid hash + u64 compute mass
        r_u8(r)
        r_hash(r)
        r_u64(r)
    return Transaction(version, inputs, outputs, lock_time, subnetwork, gas, payload, storage_mass)


def _encode_header_fields(w, h) -> None:
    w_u16(w, h.version)
    w_u32(w, len(h.parents_by_level))
    for level in h.parents_by_level:
        w_u32(w, len(level))
        for p in level:
            w_hash(w, p)
    w_hash(w, h.hash_merkle_root)
    w_hash(w, h.accepted_id_merkle_root)
    w_hash(w, h.utxo_commitment)
    w_u64(w, h.timestamp)
    w_u32(w, h.bits)
    w_u64(w, h.nonce)
    w_u64(w, h.daa_score)
    w_uint192(w, h.blue_work)
    w_u64(w, h.blue_score)
    w_hash(w, h.pruning_point)


def _decode_header_fields(r) -> dict:
    version = r_u16(r)
    parents = []
    for _ in range(r_u32(r)):
        parents.append([r_hash(r) for _ in range(r_u32(r))])
    return {
        "version": version,
        "parents_by_level": parents,
        "hash_merkle_root": r_hash(r),
        "accepted_id_merkle_root": r_hash(r),
        "utxo_commitment": r_hash(r),
        "timestamp": r_u64(r),
        "bits": r_u32(r),
        "nonce": r_u64(r),
        "daa_score": r_u64(r),
        "blue_work": r_uint192(r),
        "blue_score": r_u64(r),
        "pruning_point": r_hash(r),
    }


def encode_raw_header(w, h) -> None:
    """header.rs:286-305 (RpcRawHeader: no hash field)."""
    w_u16(w, 1)
    _encode_header_fields(w, h)


def decode_raw_header(r):
    from kaspa_tpu.consensus.model import Header

    r_u16(r)
    f = _decode_header_fields(r)
    return Header(**f)


def encode_header(w, h) -> None:
    """header.rs:148-167 (RpcHeader: leads with the block hash)."""
    w_u16(w, 1)
    w_hash(w, h.hash)
    _encode_header_fields(w, h)


def encode_submit_block_request(w, block, allow_non_daa_blocks: bool = False) -> None:
    """message.rs:34-41: struct version + RpcRawBlock + bool."""
    w_u16(w, 1)
    w_u16(w, 1)  # RpcRawBlock struct version (block.rs:45-52)
    encode_raw_header(w, block.header)
    w_u32(w, len(block.transactions))
    for tx in block.transactions:
        encode_tx(w, tx)
    w_bool(w, allow_non_daa_blocks)


def decode_submit_block_request(r):
    from kaspa_tpu.consensus.model.block import Block

    r_u16(r)
    r_u16(r)  # raw block struct version
    header = decode_raw_header(r)
    txs = [decode_tx(r) for _ in range(r_u32(r))]
    allow_non_daa = r_bool(r)
    return Block(header, txs), allow_non_daa


# SubmitBlockRejectReason discriminants (message.rs:54-60, use_discriminant)
REJECT_BLOCK_INVALID = 1
REJECT_IS_IN_IBD = 2
REJECT_ROUTE_IS_FULL = 3


def encode_submit_block_response(w, reject_reason: int | None) -> None:
    """message.rs:98-103; SubmitBlockReport borsh enum: 0=Success,
    1=Reject(reason) (message.rs:82-85)."""
    w_u16(w, 1)
    if reject_reason is None:
        w_u8(w, 0)
    else:
        w_u8(w, 1)
        w_u8(w, reject_reason)


def decode_submit_block_response(r) -> int | None:
    r_u16(r)
    if r_u8(r) == 0:
        return None
    return r_u8(r)


def encode_block_added_notification(w, block, verbose: dict) -> None:
    """message.rs:2991-2996 wrapping RpcBlock (block.rs:23-31) with its
    verbose data (block.rs:80-92)."""
    w_u16(w, 1)
    w_u16(w, 1)  # RpcBlock struct version
    encode_header(w, block.header)
    w_u32(w, len(block.transactions))
    for tx in block.transactions:
        encode_tx(w, tx)
    w_u8(w, 1)  # Option<RpcBlockVerboseData>: Some
    w_u8(w, 1)  # verbose struct version
    w_hash(w, block.hash)
    w_f64(w, verbose.get("difficulty", 0.0))
    w_hash(w, verbose.get("selected_parent_hash", bytes(32)))
    ids = [tx.id() for tx in block.transactions]
    w_u32(w, len(ids))
    for i in ids:
        w_hash(w, i)
    w_bool(w, verbose.get("is_header_only", False))
    w_u64(w, verbose.get("blue_score", block.header.blue_score))
    for key in ("children_hashes", "merge_set_blues_hashes", "merge_set_reds_hashes"):
        hs = verbose.get(key, [])
        w_u32(w, len(hs))
        for h in hs:
            w_hash(w, h)
    w_bool(w, verbose.get("is_chain_block", False))


# ---------------------------------------------------------------------------
# serving-tier payloads: UTXO queries + UtxosChanged (message.rs
# GetUtxosByAddresses*/GetBalanceByAddress*/GetCoinSupply*/UtxosChanged*)
# ---------------------------------------------------------------------------

def encode_utxo_entry_rpc(w, e) -> None:
    """RpcUtxoEntry (tx.rs:361-370): amount, spk, daa score, coinbase flag,
    plus the version-2 Option<covenant id> this consensus carries."""
    w_u16(w, 2)
    w_u64(w, e.amount)
    w_u16(w, e.script_public_key.version)
    w_bytes(w, e.script_public_key.script)
    w_u64(w, e.block_daa_score)
    w_bool(w, e.is_coinbase)
    if e.covenant_id is None:
        w_u8(w, 0)
    else:
        w_u8(w, 1)
        w_hash(w, e.covenant_id)


def decode_utxo_entry_rpc(r):
    from kaspa_tpu.consensus.model import ScriptPublicKey, UtxoEntry

    r_u16(r)
    amount = r_u64(r)
    spk = ScriptPublicKey(r_u16(r), r_bytes(r))
    daa = r_u64(r)
    coinbase = r_bool(r)
    cov = r_hash(r) if r_u8(r) == 1 else None
    return UtxoEntry(amount, spk, daa, coinbase, cov)


def encode_utxos_by_addresses_entry(w, address: str | None, outpoint, entry) -> None:
    """RpcUtxosByAddressesEntry (message.rs:1764-1771): Option<address>
    (None for scripts with no standard address form) + outpoint + entry."""
    w_u16(w, 1)
    if address is None:
        w_u8(w, 0)
    else:
        w_u8(w, 1)
        w_string(w, address)
    encode_outpoint(w, outpoint)
    encode_utxo_entry_rpc(w, entry)


def decode_utxos_by_addresses_entry(r):
    r_u16(r)
    address = r_string(r) if r_u8(r) == 1 else None
    return address, decode_outpoint(r), decode_utxo_entry_rpc(r)


def encode_get_utxos_by_addresses_request(w, addresses: list[str]) -> None:
    w_u16(w, 1)
    w_u32(w, len(addresses))
    for a in addresses:
        w_string(w, a)


def decode_get_utxos_by_addresses_request(r) -> list[str]:
    r_u16(r)
    return [r_string(r) for _ in range(r_u32(r))]


def encode_get_utxos_by_addresses_response(w, entries) -> None:
    """entries: (address|None, outpoint, UtxoEntry) triples."""
    w_u16(w, 1)
    w_u32(w, len(entries))
    for address, outpoint, entry in entries:
        encode_utxos_by_addresses_entry(w, address, outpoint, entry)


def decode_get_utxos_by_addresses_response(r):
    r_u16(r)
    return [decode_utxos_by_addresses_entry(r) for _ in range(r_u32(r))]


def encode_get_balance_by_address_request(w, address: str) -> None:
    w_u16(w, 1)
    w_string(w, address)


def decode_get_balance_by_address_request(r) -> str:
    r_u16(r)
    return r_string(r)


def encode_get_balance_by_address_response(w, balance: int) -> None:
    w_u16(w, 1)
    w_u64(w, balance)


def decode_get_balance_by_address_response(r) -> int:
    r_u16(r)
    return r_u64(r)


# consensus/core/src/constants.rs MAX_SOMPI: 29B KAS in sompi
MAX_SOMPI = 29_000_000_000 * 100_000_000


def encode_get_coin_supply_request(w) -> None:
    w_u16(w, 1)


def decode_get_coin_supply_request(r) -> dict:
    r_u16(r)
    return {}


def encode_get_coin_supply_response(w, circulating_sompi: int, max_sompi: int = MAX_SOMPI) -> None:
    """message.rs GetCoinSupplyResponse: max then circulating."""
    w_u16(w, 1)
    w_u64(w, max_sompi)
    w_u64(w, circulating_sompi)


def decode_get_coin_supply_response(r) -> dict:
    r_u16(r)
    return {"max_sompi": r_u64(r), "circulating_sompi": r_u64(r)}


def encode_utxos_changed_notification(w, added, removed, address_prefix: str | None = None) -> None:
    """message.rs:3127-3133 UtxosChangedNotification: added/removed entry
    vecs.  ``added``/``removed`` are (outpoint, UtxoEntry) pairs; addresses
    are recovered from the script pubkey (None when nonstandard)."""
    w_u16(w, 1)
    for pairs in (added, removed):
        w_u32(w, len(pairs))
        for outpoint, entry in pairs:
            address = None
            if address_prefix is not None:
                from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

                try:
                    address = extract_script_pub_key_address(entry.script_public_key, address_prefix).to_string()
                except Exception:  # noqa: BLE001 - nonstandard script: no address form
                    address = None
            encode_utxos_by_addresses_entry(w, address, outpoint, entry)


def decode_utxos_changed_notification(r) -> dict:
    r_u16(r)
    added = [decode_utxos_by_addresses_entry(r) for _ in range(r_u32(r))]
    removed = [decode_utxos_by_addresses_entry(r) for _ in range(r_u32(r))]
    return {"added": added, "removed": removed}


def encode_subscribe_request(w, event_op: int, addresses: list[str] | None = None) -> None:
    """Subscribe payload: the notification op, plus (UtxosChanged only) the
    bech32 address scope — an empty vec subscribes to all addresses."""
    w_u32(w, event_op)
    if event_op == OP_UTXOS_CHANGED_NOTIFICATION:
        addrs = addresses or []
        w_u32(w, len(addrs))
        for a in addrs:
            w_string(w, a)


# ---------------------------------------------------------------------------
# framing + dispatch
# ---------------------------------------------------------------------------

def encode_frame(kind: int, op: int, payload: bytes, msg_id: int | None = None) -> bytes:
    w = io.BytesIO()
    w_u8(w, kind)
    if kind in (KIND_REQUEST, KIND_RESPONSE, KIND_ERROR):
        w_u64(w, msg_id or 0)
    w_u32(w, op)
    w.write(payload)
    return w.getvalue()


def decode_frame(data: bytes):
    r = io.BytesIO(data)
    kind = r_u8(r)
    msg_id = r_u64(r) if kind in (KIND_REQUEST, KIND_RESPONSE, KIND_ERROR) else None
    op = r_u32(r)
    return kind, msg_id, op, r


def handle_frame(daemon, data: bytes, notification_sink=None, subscriber_ref=None, stop=None) -> bytes:
    """Dispatch one Borsh wRPC request frame; returns the response frame.

    The server side of the reference's Borsh-encoding wRPC endpoint
    (rpc/wrpc/server/src/server.rs) over this module's documented frame.
    ``subscriber_ref`` is the connection's one-slot serving Subscriber cell
    (created lazily on first subscribe, torn down by the transport).
    """
    msg_id = 0
    try:
        kind, msg_id, op, r = decode_frame(data)
        if kind != KIND_REQUEST:
            raise ValueError(f"unexpected frame kind {kind}")
        if op == OP_GET_INFO:
            decode_get_info_request(r)
            info = daemon.dispatch("getInfo", {})
            w = io.BytesIO()
            encode_get_info_response(w, info)
            return encode_frame(KIND_RESPONSE, op, w.getvalue(), msg_id)
        if op == OP_SUBMIT_BLOCK:
            from kaspa_tpu.consensus.consensus import RuleError
            from kaspa_tpu.core.log import get_logger

            block, _allow_non_daa = decode_submit_block_request(r)
            w = io.BytesIO()
            try:
                with daemon._dispatch_lock:
                    # graftlint: allow(blocking-under-lock) -- borsh submit serializes with the RPC mutation path under the dispatch lock; insert+unorphan device waits are deliberate
                    daemon.node.submit_block(block)
                encode_submit_block_response(w, None)
            except (RuleError, ValueError) as e:
                # consensus rejection: the typed reject report
                get_logger("wrpc.borsh").info("block %s rejected: %s", block.hash.hex()[:16], e)
                encode_submit_block_response(w, REJECT_BLOCK_INVALID)
            # internal failures propagate to the KIND_ERROR frame below —
            # a miner must not read a node bug as "your block was invalid"
            return encode_frame(KIND_RESPONSE, op, w.getvalue(), msg_id)
        if op == OP_GET_UTXOS_BY_ADDRESSES:
            from kaspa_tpu.crypto.addresses import Address, pay_to_address_script

            addresses = decode_get_utxos_by_addresses_request(r)
            entries = []
            with daemon._dispatch_lock:
                index = daemon.rpc._require_index()
                for a in addresses:
                    spk = pay_to_address_script(Address.from_string(a))
                    utxos = index.get_utxos_by_script(spk.script)
                    for outpoint in sorted(utxos, key=lambda o: (o.transaction_id, o.index)):
                        entries.append((a, outpoint, utxos[outpoint]))
            w = io.BytesIO()
            encode_get_utxos_by_addresses_response(w, entries)
            return encode_frame(KIND_RESPONSE, op, w.getvalue(), msg_id)
        if op == OP_GET_BALANCE_BY_ADDRESS:
            address = decode_get_balance_by_address_request(r)
            with daemon._dispatch_lock:
                balance = daemon.rpc.get_balance_by_address(address)
            w = io.BytesIO()
            encode_get_balance_by_address_response(w, balance)
            return encode_frame(KIND_RESPONSE, op, w.getvalue(), msg_id)
        if op == OP_GET_COIN_SUPPLY:
            decode_get_coin_supply_request(r)
            with daemon._dispatch_lock:
                supply = daemon.rpc.get_coin_supply()["circulating_sompi"]
            w = io.BytesIO()
            encode_get_coin_supply_response(w, supply)
            return encode_frame(KIND_RESPONSE, op, w.getvalue(), msg_id)
        if op == OP_SUBSCRIBE:
            event_op = r_u32(r)
            scripts = None
            if event_op == OP_BLOCK_ADDED_NOTIFICATION:
                event = "block-added"
            elif event_op == OP_UTXOS_CHANGED_NOTIFICATION:
                event = "utxos-changed"
                addrs = [r_string(r) for _ in range(r_u32(r))]
                if addrs:
                    from kaspa_tpu.crypto.addresses import Address, pay_to_address_script

                    scripts = {pay_to_address_script(Address.from_string(a)).script for a in addrs}
            else:
                raise ValueError(f"unsupported subscription op {event_op}")
            # route through the serving broadcaster: one lazily-created
            # Borsh subscriber per connection, bounded queue + dedicated
            # sender thread so the full-block/diff encode never runs on the
            # consensus thread publishing the event
            with daemon._dispatch_lock:
                if subscriber_ref[0] is None:
                    subscriber_ref[0] = daemon.broadcaster.register(
                        daemon.make_borsh_subscriber(notification_sink, stop)
                    )
                daemon.broadcaster.subscribe(subscriber_ref[0], event, scripts)
            return encode_frame(KIND_RESPONSE, op, b"", msg_id)
        raise ValueError(f"unsupported borsh op {op}")
    except Exception as e:  # noqa: BLE001 - wire boundary
        w = io.BytesIO()
        w_string(w, str(e))
        return encode_frame(KIND_ERROR, 0, w.getvalue(), msg_id or 0)


def make_block_added_frame(block, verbose: dict | None = None) -> bytes:
    w = io.BytesIO()
    encode_block_added_notification(w, block, verbose or {})
    return encode_frame(KIND_NOTIFICATION, OP_BLOCK_ADDED_NOTIFICATION, w.getvalue())


def make_utxos_changed_frame(n, address_prefix: str | None = None) -> bytes:
    w = io.BytesIO()
    encode_utxos_changed_notification(w, n.data.get("added", []), n.data.get("removed", []), address_prefix)
    return encode_frame(KIND_NOTIFICATION, OP_UTXOS_CHANGED_NOTIFICATION, w.getvalue())


def encode_notification(n, address_prefix: str | None = None) -> bytes | None:
    """Serving-tier encoder: one Notification -> one Borsh frame, or None
    when this encoding has no codec for the event (the subscriber skips
    it).  Runs on the subscriber's sender thread."""
    if n.event_type == "block-added":
        return make_block_added_frame(n.data["block"])
    if n.event_type == "utxos-changed":
        return make_utxos_changed_frame(n, address_prefix)
    return None
