"""RpcCoreService: the RPC API implementation over consensus/mempool/indexes.

Reference: rpc/core/src/api/rpc.rs (the ~45-method RpcApi trait) implemented
by rpc/service/src/service.rs against consensus sessions, the mining
manager, and the utxoindex.  This module is the transport-independent core:
the gRPC/wRPC server stacks (rpc/grpc, rpc/wrpc) bind these methods to the
wire in a later milestone; notifications flow through the same
kaspa_tpu.notify chain the reference threads through RpcCoreService.

Methods mirror the reference's names (get_block, get_block_dag_info,
submit_block, submit_transaction, get_utxos_by_addresses, ...) and return
plain dict/dataclass models (the Rpc* mirror types of rpc/core/src/model).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from kaspa_tpu.consensus.consensus import Consensus, RuleError
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.crypto.addresses import Address, extract_script_pub_key_address, pay_to_address_script
from kaspa_tpu.index import UtxoIndex
from kaspa_tpu.mempool import MiningManager
from kaspa_tpu.mempool.mempool import MempoolError
from kaspa_tpu.metrics import PerfMonitor
from kaspa_tpu.notify.notifier import Notifier
from kaspa_tpu.observability import snapshot as observability_snapshot
from kaspa_tpu.utils.sync import lock_trace_snapshot as _lock_trace_snapshot


class RpcError(Exception):
    """RPC-level rejection.  ``code`` is a stable machine-readable
    identifier forwarded on the wire (rpc.rs RpcError submit categories):
    clients branch on tx-orphan / tx-duplicate / tx-rbf-rejected /
    tx-fee-too-low / tx-double-spend / mempool-full / tx-gas / tx-invalid /
    node-overloaded without parsing prose.  ``node-overloaded`` (a brownout
    shed, not a verdict on the tx) additionally carries ``retry_after_ms``,
    forwarded on the wire as ``retryAfterMs`` — the client should back off
    and resubmit the identical tx."""

    def __init__(self, message: str, code: str = "rpc-error", retry_after_ms: int | None = None):
        super().__init__(message)
        self.code = code
        self.retry_after_ms = retry_after_ms


@dataclass
class ServerInfo:
    rpc_api_version: int = 1
    server_version: str = "kaspa-tpu/0.1"
    network_id: str = ""
    has_utxo_index: bool = True
    is_synced: bool = True
    virtual_daa_score: int = 0


class RpcCoreService:
    def __init__(
        self,
        consensus: Consensus,
        mining: MiningManager,
        utxoindex: UtxoIndex | None = None,
        address_prefix: str = "kaspasim",
        p2p_node=None,
        address_manager=None,
        connection_manager=None,
        shutdown_fn=None,
    ):
        self.consensus = consensus
        # the formal consensus boundary (consensus/core/src/api/mod.rs):
        # all consensus reads route through the facade, so staging swaps
        # can never race readers against internal stores
        from kaspa_tpu.consensus.api import ConsensusApi

        self.api = ConsensusApi(consensus)
        self.mining = mining
        # None => run without an index: address-based queries unavailable
        self.utxoindex = utxoindex
        self.address_prefix = address_prefix
        # p2p wiring (None => peer methods report unavailability)
        self.p2p_node = p2p_node
        self.address_manager = address_manager
        self.connection_manager = connection_manager
        self.shutdown_fn = shutdown_fn
        # daemon-installed: () -> metrics.core.MetricsSnapshot | None
        self.metrics_provider = None
        # rpc-level notifier chained onto the consensus root (the reference's
        # consensus -> notify -> index -> rpc chain)
        self.notifier = Notifier("rpc-core", parent=consensus.notification_root)
        self.perf_monitor = PerfMonitor()
        self.start_time = time.time()

    # --- node / dag info ---

    def get_server_info(self) -> ServerInfo:
        return ServerInfo(
            network_id=self.consensus.params.name,
            virtual_daa_score=self.api.get_virtual_daa_score(),
        )

    def get_block_dag_info(self) -> dict:
        return {
            "network": self.consensus.params.name,
            "block_count": self.api.get_block_count(),
            "tip_hashes": [h.hex() for h in self.api.get_tips()],
            "virtual_parent_hashes": [h.hex() for h in self.api.get_virtual_parents_ordered()],
            "difficulty_bits": self.api.get_virtual_bits(),
            "past_median_time": self.api.get_virtual_past_median_time(),
            "virtual_daa_score": self.api.get_virtual_daa_score(),
            "sink": self.api.get_sink().hex(),
            "pruning_point": self.api.pruning_point().hex(),
        }

    def get_sink(self) -> bytes:
        return self.api.get_sink()

    def get_sink_blue_score(self) -> int:
        return self.api.get_sink_blue_score()

    def get_virtual_chain_from_block(self, low: bytes) -> dict:
        """Selected-chain path from `low` to the sink + acceptance data."""
        if not self.api.block_exists(low):
            raise RpcError(f"block {low.hex()} not found")
        from kaspa_tpu.consensus.api import ConsensusError

        try:
            chain = self.api.get_virtual_chain_from_block(low)["added"]
        except ConsensusError as e:
            raise RpcError(str(e)) from e
        return {
            "added_chain_blocks": [h.hex() for h in chain],
            "accepted_transaction_ids": {
                h.hex(): [t.hex() for t in self.api.get_accepted_transaction_ids(h)] for h in chain
            },
        }

    # --- blocks ---

    def get_block(self, block_hash: bytes, include_transactions: bool = True) -> dict:
        if not self.api.block_exists(block_hash):
            raise RpcError(f"block {block_hash.hex()} not found")
        header = self.api.get_header(block_hash)
        out = {
            "hash": block_hash.hex(),
            "header": {
                "version": header.version,
                "parents_by_level": [[p.hex() for p in lvl] for lvl in header.parents_by_level],
                "hash_merkle_root": header.hash_merkle_root.hex(),
                "accepted_id_merkle_root": header.accepted_id_merkle_root.hex(),
                "utxo_commitment": header.utxo_commitment.hex(),
                "timestamp": header.timestamp,
                "bits": header.bits,
                "nonce": header.nonce,
                "daa_score": header.daa_score,
                "blue_work": hex(header.blue_work),
                "blue_score": header.blue_score,
                "pruning_point": header.pruning_point.hex(),
            },
            "verbose": {
                "status": self.api.get_block_status(block_hash),
                "is_chain_block": self.api.is_chain_block(block_hash),
            },
        }
        if include_transactions and self.api.has_block_body(block_hash):
            out["transactions"] = [self._tx_to_rpc(tx) for tx in self.api.get_block_transactions(block_hash)]
        return out

    def get_blocks(self, low_hash: bytes | None = None, include_transactions: bool = False) -> list[dict]:
        """Blocks in the future of `low_hash` (inclusive), or all blocks."""
        hashes = list(self.api.iter_block_hashes())
        if low_hash is not None:
            if not self.api.block_exists(low_hash):
                raise RpcError(f"block {low_hash.hex()} not found")
            hashes = [h for h in hashes if self.api.is_dag_ancestor_of(low_hash, h)]
        return [self.get_block(h, include_transactions) for h in hashes]

    def submit_block(self, block: Block) -> str:
        try:
            if self.p2p_node is not None:
                # the node path runs the concurrent pipeline + orphan/relay
                return self.p2p_node.submit_block(block)
            status = self.api.validate_and_insert_block(block)
        except RuleError as e:
            raise RpcError(f"block rejected: {e}") from e
        self.mining.handle_new_block_transactions(block.transactions, self.api.get_virtual_daa_score())
        return status

    def get_block_template(self, pay_address: str, extra_data: bytes = b"") -> Block:
        from kaspa_tpu.consensus.processes.coinbase import MinerData

        # MiningRuleEngine gate (rule_engine.rs should_mine): templates are
        # refused while the node is unsynced/disconnected, unless the
        # sync-rate rule determined the network itself stalled
        engine = getattr(self, "rule_engine", None)
        if engine is not None:
            sink_ts = self.api.get_sink_timestamp()
            if not engine.should_mine(sink_ts):
                raise RpcError("node is not synced: block templates unavailable")
        addr = Address.from_string(pay_address)
        spk = pay_to_address_script(addr)
        return self.mining.get_block_template(MinerData(spk, extra_data))

    # --- transactions ---

    def _admit_transaction(self, tx) -> list[bytes]:
        """Shared admission for submit/replacement: through the node's
        batched ingest tier when p2p is wired (concurrent submitters share
        a verify wave; accepted txs are relayed), direct otherwise.  Maps
        rejections to RpcError with the mempool's stable code, and reports
        an orphan park explicitly — the reference's submit rejects orphans
        unless allow_orphan, and a caller must be able to tell a parked tx
        from a pooled one (rpc.rs RejectedTransactionIsAnOrphan)."""
        from kaspa_tpu.consensus.processes.transaction_validator import TxRuleError

        try:
            if self.p2p_node is not None:
                evicted = self.p2p_node.submit_transaction(tx)
            else:
                evicted = self.mining.validate_and_insert_transaction(tx)
        except MempoolError as e:
            raise RpcError(
                f"transaction rejected: {e}",
                code=e.code,
                retry_after_ms=getattr(e, "retry_after_ms", None),
            ) from e
        except TxRuleError as e:
            raise RpcError(f"transaction rejected: {e}", code="tx-invalid") from e
        if tx.id() in self.mining.mempool.orphans:
            raise RpcError(
                f"transaction {tx.id().hex()} is an orphan (missing inputs); "
                "it was parked in the orphan pool awaiting its parents",
                code="tx-orphan",
            )
        return evicted

    def submit_transaction(self, tx) -> bytes:
        self._admit_transaction(tx)
        return tx.id()

    def get_mempool_entries(self, include_orphan_pool: bool = True) -> list[dict]:
        out = [
            {"transaction_id": txid.hex(), "fee": e.fee, "mass": e.mass, "is_orphan": False}
            for txid, e in self.mining.mempool.pool.items()
        ]
        if include_orphan_pool:
            out.extend(
                {"transaction_id": txid.hex(), "fee": e.fee, "mass": e.mass, "is_orphan": True}
                for txid, e in self.mining.mempool.orphans.items()
            )
        return out

    def get_mempool_entry(self, txid: bytes) -> dict:
        e = self.mining.mempool.get(txid)
        if e is not None:
            return {"transaction_id": txid.hex(), "fee": e.fee, "mass": e.mass, "is_orphan": False}
        e = self.mining.mempool.orphans.get(txid)
        if e is not None:
            return {"transaction_id": txid.hex(), "fee": e.fee, "mass": e.mass, "is_orphan": True}
        raise RpcError(f"transaction {txid.hex()} not in mempool")

    # --- utxos / balances (utxoindex-backed, rpc.rs get_utxos_by_addresses) ---

    def _require_index(self):
        if self.utxoindex is None:
            raise RpcError("method unavailable without --utxoindex")
        return self.utxoindex

    def get_utxos_by_addresses(self, addresses: list[str]) -> list[dict]:
        self._require_index()
        out = []
        for s in addresses:
            addr = Address.from_string(s)
            spk = pay_to_address_script(addr)
            for outpoint, entry in self.utxoindex.get_utxos_by_script(spk.script).items():
                out.append(
                    {
                        "address": s,
                        "outpoint": {"transaction_id": outpoint.transaction_id.hex(), "index": outpoint.index},
                        "utxo_entry": {
                            "amount": entry.amount,
                            "block_daa_score": entry.block_daa_score,
                            "is_coinbase": entry.is_coinbase,
                        },
                    }
                )
        return out

    def get_balance_by_address(self, address: str) -> int:
        spk = pay_to_address_script(Address.from_string(address))
        return self._require_index().get_balance_by_script(spk.script)

    def get_coin_supply(self) -> dict:
        return {"circulating_sompi": self._require_index().get_circulating_supply()}

    # --- subscriptions (notify_* RPCs) ---

    def register_listener(self, callback) -> int:
        return self.notifier.register(callback)

    def start_notify(self, listener_id: int, event_type: str, addresses: list[str] | None = None) -> None:
        spks = None
        if addresses is not None:
            spks = {pay_to_address_script(Address.from_string(a)).script for a in addresses}
        self.notifier.start_notify(listener_id, event_type, spks)

    def stop_notify(self, listener_id: int, event_type: str) -> None:
        self.notifier.stop_notify(listener_id, event_type)

    # --- metrics (rpc.rs get_metrics -> metrics/core MetricsSnapshot) ---

    def get_metrics(self) -> dict:
        from dataclasses import asdict

        sc = self.consensus.transaction_validator.sig_cache
        obs = observability_snapshot()
        return {
            "uptime_seconds": time.time() - self.start_time,
            "block_count": self.api.get_block_count(),
            "tip_count": self.api.get_tips_len(),
            "mempool_size": len(self.mining.mempool),
            "virtual_daa_score": self.api.get_virtual_daa_score(),
            "sig_cache_hits": sc.hits,
            "sig_cache_misses": sc.misses,
            "process_counters": asdict(self.consensus.counters.snapshot()),
            "process_metrics": asdict(self.perf_monitor.sample()),
            # per-lock acquisition/hold aggregates when KASPA_TPU_LOCK_DEBUG
            # is on (the reference's semaphore-trace analog); {} otherwise
            "lock_trace": _lock_trace_snapshot(),
            # grouped snapshot with derived rates (metrics/core/src/data.rs),
            # sampled by the daemon's tick service
            "snapshot": (
                {"unixtime_millis": snap.unixtime_millis, **snap.values}
                if self.metrics_provider is not None and (snap := self.metrics_provider()) is not None
                else None
            ),
            # span/histogram/counter registry (observability/core): per-stage
            # pipeline latencies, secp batch occupancy, jit compile counts,
            # store cache hit rates — the same tree prom.render() exports
            "observability": obs,
            # serving-plane latency observatory (the Broadcaster collector):
            # fanout state + per-stage block-accept -> wire lag quantiles
            # (serving_lag_ms), surfaced top-level so dashboards don't dig
            "serving": obs.get("serving", {}),
        }

    def get_metrics_prometheus(self) -> str:
        """The observability registry in Prometheus text exposition format
        (the reference daemon's --prometheus endpoint analog)."""
        from kaspa_tpu.observability import prom

        return prom.render()

    def get_traces(self, limit: int = 32, verbose: bool = False) -> dict:
        """Flight-recorder surface: recent completed block traces with
        their critical-path attribution.  ``verbose`` returns the full
        span trees (trace_report.py / Perfetto input); the default is the
        per-block summary (spans, threads, wall ms, top stages)."""
        from kaspa_tpu.observability import flight

        out = {"enabled": flight.enabled(), "traces": flight.summaries(limit=limit)}
        if verbose:
            out["full"] = flight.traces()[-limit:]
        return out

    # --- node info / misc (rpc.rs ping/get_info/get_current_network/...) ---

    def ping(self) -> dict:
        return {}

    def get_current_network(self) -> str:
        return self.consensus.params.name

    def get_info(self) -> dict:
        return {
            "p2p_id": self.consensus.params.name,
            "mempool_size": len(self.mining.mempool),
            "server_version": "kaspa-tpu/0.2",
            "is_utxo_indexed": self.utxoindex is not None,
            "is_synced": True,
            "has_notify_command": True,
            "has_message_id": True,
        }

    def get_block_count(self) -> dict:
        n = self.api.get_block_count()
        return {"header_count": n, "block_count": n}

    def get_sync_status(self) -> bool:
        return True

    def get_system_info(self) -> dict:
        from kaspa_tpu.utils.sysinfo import system_info

        return system_info()

    def shutdown(self) -> dict:
        if self.shutdown_fn is None:
            raise RpcError("shutdown is not wired on this node")
        self.shutdown_fn()
        return {}

    def get_subnetwork(self, subnetwork_id: str) -> dict:
        raise RpcError(f"subnetwork {subnetwork_id} not found")

    def get_seq_commit_lane_proof(self, *_args) -> dict:
        raise RpcError("seq-commit lanes are not active (pre-Toccata ruleset)")

    # --- headers / chain queries ---

    def get_headers(self, start_hash: bytes, limit: int = 100, is_ascending: bool = True) -> list[dict]:
        if not self.api.block_exists(start_hash):
            raise RpcError(f"block {start_hash.hex()} not found")
        out = []
        cur = start_hash
        if is_ascending:
            # follow the selected chain toward the sink
            sink = self.api.get_sink()
            if not self.api.is_chain_ancestor_of(cur, sink):
                raise RpcError("start hash is not on the selected chain")
            while len(out) < limit:
                out.append(self.get_block(cur, include_transactions=False)["header"] | {"hash": cur.hex()})
                if cur == sink:
                    break
                cur = self.api.get_next_chain_ancestor(sink, cur)
        else:
            genesis = self.consensus.params.genesis.hash
            while len(out) < limit:
                out.append(self.get_block(cur, include_transactions=False)["header"] | {"hash": cur.hex()})
                if cur == genesis:
                    break
                cur = self.api.get_selected_parent(cur)
        return out

    def get_current_block_color(self, block_hash: bytes) -> dict:
        """Blue/red of `block_hash` from the virtual's perspective (rpc.rs
        get_current_block_color -> ConsensusApi get_current_block_color)."""
        from kaspa_tpu.consensus.api import ConsensusError

        if not self.api.block_exists(block_hash):
            raise RpcError(f"block {block_hash.hex()} not found")
        try:
            return {"blue": self.api.get_current_block_color(block_hash)}
        except ConsensusError as e:
            raise RpcError(str(e)) from e

    def get_daa_score_timestamp_estimate(self, daa_scores: list[int]) -> list[int]:
        """Timestamps of the selected-chain blocks nearest each DAA score."""
        chain = []
        cur = self.api.get_sink()
        genesis = self.consensus.params.genesis.hash
        while True:
            chain.append(cur)
            if cur == genesis:
                break
            cur = self.api.get_selected_parent(cur)
        chain.reverse()
        scores = [self.api.get_daa_score(h) for h in chain]
        import bisect

        out = []
        for q in daa_scores:
            i = min(bisect.bisect_left(scores, q), len(chain) - 1)
            out.append(self.api.get_block_timestamp(chain[i]))
        return out

    def estimate_network_hashes_per_second(self, window_size: int = 1000, start_hash: bytes | None = None) -> int:
        """Σ chain-block work over the window / elapsed time (rpc.rs) —
        delegated to the ConsensusApi estimator."""
        from kaspa_tpu.consensus.api import ConsensusError

        try:
            return self.api.estimate_network_hashes_per_second(start_hash, window_size)
        except ConsensusError as e:
            raise RpcError(str(e)) from e

    def get_block_reward_info(self, block_hash: bytes | None = None) -> dict:
        h = block_hash if block_hash is not None else self.api.get_sink()
        if not self.api.block_exists(h):
            raise RpcError(f"block {h.hex()} not found")
        daa = self.api.get_daa_score(h)
        subsidy = self.consensus.coinbase_manager.calc_block_subsidy(daa)
        return {"block_hash": h.hex(), "daa_score": daa, "subsidy": subsidy}

    def resolve_finality_conflict(self, finality_block_hash: bytes) -> dict:
        """Operator acknowledgement of a finality conflict (rpc.rs
        resolve_finality_conflict): clears the tracked conflicts and emits
        FinalityConflictResolved; adopting the competing chain requires a
        resync from a peer carrying it (the reference likewise requires
        manual intervention)."""
        acked = self.api.acknowledge_finality_conflicts()
        if not acked:
            raise RpcError("no active finality conflict to resolve")
        from kaspa_tpu.notify.notifier import Notification

        self.consensus.notification_root.notify(
            Notification(
                "finality-conflict-resolved",
                {"finality_block_hash": finality_block_hash.hex()},
            )
        )
        return {}

    _RETURN_ADDRESS_DAA_SLACK = 2_000  # search radius around the claimed score

    def get_utxo_return_address(self, txid: bytes, accepting_block_daa_score: int) -> str:
        """Source address of a tx's first input (rpc.rs get_utxo_return_address).

        The accepting DAA score narrows the search to nearby accepting chain
        blocks; the funding output is then resolved from bodies in the
        accepting block's past within the same bounded window (the reference
        resolves it via its tx-index; pruned or out-of-window history raises)."""
        lo = accepting_block_daa_score - self._RETURN_ADDRESS_DAA_SLACK
        hi = accepting_block_daa_score + self._RETURN_ADDRESS_DAA_SLACK
        src_tx = None
        for bh, txids in self.api.iter_acceptance():
            daa = self.api.get_daa_score(bh)
            if accepting_block_daa_score and not (lo <= daa <= hi):
                continue
            if txid not in txids:
                continue
            # scan the merged blocks' bodies for the tx
            for cand in [bh, *self.api.get_ghostdag_data(bh).unordered_mergeset()]:
                if not self.api.has_block_body(cand):
                    continue
                for tx in self.api.get_block_transactions(cand):
                    if tx.id() == txid:
                        src_tx = tx
                        break
            if src_tx is not None:
                break
        if src_tx is None:
            raise RpcError("transaction not found in accepted history near the given DAA score")
        if not src_tx.inputs:
            raise RpcError("transaction is coinbase; no return address")
        prev = src_tx.inputs[0].previous_outpoint
        spk = self._find_output_script(prev, hi)
        if spk is None:
            raise RpcError("source output unavailable (pruned or beyond search window)")
        return extract_script_pub_key_address(spk, self.address_prefix).to_string()

    def _find_output_script(self, outpoint, max_daa: int):
        """Bounded body search for a funding output: only blocks below the
        acceptance window's upper DAA bound are scanned."""
        return self.api.find_output_script(outpoint, max_daa)

    # --- fees ---

    def get_fee_estimate(self) -> dict:
        est = self.mining.get_fee_estimate()
        bucket = lambda b: {"feerate": b.feerate, "estimated_seconds": b.estimated_seconds}  # noqa: E731
        return {
            "priority_bucket": bucket(est.priority_bucket),
            "normal_buckets": [bucket(b) for b in est.normal_buckets],
            "low_buckets": [bucket(b) for b in est.low_buckets],
        }

    def get_fee_estimate_experimental(self, verbose: bool = False) -> dict:
        out = {"estimate": self.get_fee_estimate()}
        if verbose:
            mp = self.mining.mempool
            out["verbose"] = {
                "mempool_ready_transactions_count": len(mp.frontier),
                "mempool_ready_transactions_total_mass": mp.frontier.total_mass,
                "network_mass_per_second": self.consensus.params.max_block_mass
                * max(1, round(1000 / self.consensus.params.target_time_per_block)),
            }
        return out

    def submit_transaction_replacement(self, tx) -> dict:
        """RBF submission: returns the replaced txid (rpc.rs)."""
        evicted = self._admit_transaction(tx)
        return {
            "transaction_id": tx.id().hex(),
            "replaced_transaction_ids": [t.hex() for t in evicted],
        }

    # --- addresses / balances (plural + mempool-by-address) ---

    def get_balances_by_addresses(self, addresses: list[str]) -> list[dict]:
        return [
            {"address": a, "balance": self.get_balance_by_address(a)} for a in addresses
        ]

    def get_mempool_entries_by_addresses(self, addresses: list[str]) -> list[dict]:
        spk_to_addr = {
            pay_to_address_script(Address.from_string(a)).script: a for a in addresses
        }
        out = {a: {"address": a, "sending": [], "receiving": []} for a in addresses}
        pool = self.mining.mempool.pool
        view = self.api.get_virtual_utxo_view()
        for txid, e in pool.items():
            for o in e.tx.outputs:
                a = spk_to_addr.get(o.script_public_key.script)
                if a is not None:
                    out[a]["receiving"].append(txid.hex())
            for inp in e.tx.inputs:
                # resolve the spent output's script: virtual UTXO set first,
                # then an in-pool parent's outputs (chained spend)
                op = inp.previous_outpoint
                entry = view.get(op)
                if entry is not None:
                    spk = entry.script_public_key.script
                else:
                    parent = pool.get(op.transaction_id)
                    if parent is None or op.index >= len(parent.tx.outputs):
                        continue
                    spk = parent.tx.outputs[op.index].script_public_key.script
                a = spk_to_addr.get(spk)
                if a is not None:
                    out[a]["sending"].append(txid.hex())
        return list(out.values())

    # --- peers (addressmanager/connectionmanager-backed) ---

    def _require_p2p(self):
        if self.p2p_node is None:
            raise RpcError("p2p methods unavailable: node runs without a P2P stack")
        return self.p2p_node

    def add_peer(self, address: str, is_permanent: bool = False) -> dict:
        self._require_p2p()
        if self.connection_manager is None:
            raise RpcError("connection manager not wired")
        from kaspa_tpu.p2p.address_manager import NetAddress

        na = NetAddress.parse(address)
        if self.address_manager is not None:
            self.address_manager.add_address(na)
        self.connection_manager.add_connection_request(na, is_permanent)
        return {}

    def get_connected_peer_info(self) -> list[dict]:
        node = self._require_p2p()
        out = []
        for peer in list(node.peers):
            addr = getattr(peer, "peer_address", None)
            out.append(
                {
                    "id": hex(id(peer) & 0xFFFFFFFF),
                    "address": str(addr) if addr else "in-process",
                    "is_outbound": getattr(peer, "outbound", False),
                    "handshaken": getattr(peer, "handshaken", True),
                }
            )
        return out

    def get_connections(self) -> dict:
        node = self._require_p2p()
        peers = list(node.peers)
        return {
            "clients": 0,
            "peers": len(peers),
            "outbound": sum(1 for p in peers if getattr(p, "outbound", False)),
        }

    def get_peer_addresses(self) -> dict:
        if self.address_manager is None:
            raise RpcError("address manager not wired")
        return {
            "known_addresses": [str(a) for a in self.address_manager.get_all_addresses()],
            "banned_addresses": self.address_manager.get_all_banned_addresses(),
        }

    def ban(self, ip: str) -> dict:
        if self.address_manager is None:
            raise RpcError("address manager not wired")
        self.address_manager.ban(ip)
        node = self.p2p_node
        if node is not None:
            for peer in list(node.peers):
                addr = getattr(peer, "peer_address", None)
                if addr is not None and addr.ip == ip and hasattr(peer, "close"):
                    peer.close()
        return {}

    def unban(self, ip: str) -> dict:
        if self.address_manager is None:
            raise RpcError("address manager not wired")
        self.address_manager.unban(ip)
        return {}

    def unregister_listener(self, listener_id: int) -> None:
        self.notifier.unregister(listener_id)

    # --- helpers ---

    def _tx_to_rpc(self, tx) -> dict:
        d = {
            "transaction_id": tx.id().hex(),
            "version": tx.version,
            "lock_time": tx.lock_time,
            "gas": tx.gas,
            "payload": tx.payload.hex(),
            "inputs": [
                {
                    "previous_outpoint": {
                        "transaction_id": i.previous_outpoint.transaction_id.hex(),
                        "index": i.previous_outpoint.index,
                    },
                    "signature_script": i.signature_script.hex(),
                    "sequence": i.sequence,
                }
                for i in tx.inputs
            ],
            "outputs": [],
        }
        for o in tx.outputs:
            entry = {"amount": o.value, "script_public_key": o.script_public_key.script.hex()}
            try:
                entry["address"] = extract_script_pub_key_address(o.script_public_key, self.address_prefix).to_string()
            except Exception:
                pass
            d["outputs"].append(entry)
        return d
