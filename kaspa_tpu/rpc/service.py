"""RpcCoreService: the RPC API implementation over consensus/mempool/indexes.

Reference: rpc/core/src/api/rpc.rs (the ~45-method RpcApi trait) implemented
by rpc/service/src/service.rs against consensus sessions, the mining
manager, and the utxoindex.  This module is the transport-independent core:
the gRPC/wRPC server stacks (rpc/grpc, rpc/wrpc) bind these methods to the
wire in a later milestone; notifications flow through the same
kaspa_tpu.notify chain the reference threads through RpcCoreService.

Methods mirror the reference's names (get_block, get_block_dag_info,
submit_block, submit_transaction, get_utxos_by_addresses, ...) and return
plain dict/dataclass models (the Rpc* mirror types of rpc/core/src/model).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from kaspa_tpu.consensus.consensus import Consensus, RuleError
from kaspa_tpu.consensus.model.block import Block
from kaspa_tpu.crypto.addresses import Address, extract_script_pub_key_address, pay_to_address_script
from kaspa_tpu.index import UtxoIndex
from kaspa_tpu.mempool import MiningManager
from kaspa_tpu.mempool.mempool import MempoolError
from kaspa_tpu.metrics import PerfMonitor
from kaspa_tpu.notify.notifier import Notifier


class RpcError(Exception):
    pass


@dataclass
class ServerInfo:
    rpc_api_version: int = 1
    server_version: str = "kaspa-tpu/0.1"
    network_id: str = ""
    has_utxo_index: bool = True
    is_synced: bool = True
    virtual_daa_score: int = 0


class RpcCoreService:
    def __init__(self, consensus: Consensus, mining: MiningManager, utxoindex: UtxoIndex | None = None, address_prefix: str = "kaspasim"):
        self.consensus = consensus
        self.mining = mining
        # None => run without an index: address-based queries unavailable
        self.utxoindex = utxoindex
        self.address_prefix = address_prefix
        # rpc-level notifier chained onto the consensus root (the reference's
        # consensus -> notify -> index -> rpc chain)
        self.notifier = Notifier("rpc-core", parent=consensus.notification_root)
        self.perf_monitor = PerfMonitor()
        self.start_time = time.time()

    # --- node / dag info ---

    def get_server_info(self) -> ServerInfo:
        return ServerInfo(
            network_id=self.consensus.params.name,
            virtual_daa_score=self.consensus.get_virtual_daa_score(),
        )

    def get_block_dag_info(self) -> dict:
        vs = self.consensus.virtual_state
        return {
            "network": self.consensus.params.name,
            "block_count": len(self.consensus.storage.headers._headers) - 1,
            "tip_hashes": sorted(h.hex() for h in self.consensus.tips),
            "virtual_parent_hashes": [h.hex() for h in vs.parents],
            "difficulty_bits": vs.bits,
            "past_median_time": vs.past_median_time,
            "virtual_daa_score": vs.daa_score,
            "sink": self.consensus.sink().hex(),
            "pruning_point": self.consensus.params.genesis.hash.hex(),
        }

    def get_sink(self) -> bytes:
        return self.consensus.sink()

    def get_sink_blue_score(self) -> int:
        return self.consensus.storage.ghostdag.get_blue_score(self.consensus.sink())

    def get_virtual_chain_from_block(self, low: bytes) -> dict:
        """Selected-chain path from `low` to the sink + acceptance data."""
        if not self.consensus.storage.headers.has(low):
            raise RpcError(f"block {low.hex()} not found")
        chain = []
        cur = self.consensus.sink()
        while cur != low:
            chain.append(cur)
            if cur == self.consensus.params.genesis.hash:
                raise RpcError(f"block {low.hex()} is not a chain ancestor of the sink")
            cur = self.consensus.storage.ghostdag.get_selected_parent(cur)
        chain.reverse()
        return {
            "added_chain_blocks": [h.hex() for h in chain],
            "accepted_transaction_ids": {
                h.hex(): [t.hex() for t in self.consensus.acceptance_data.get(h, [])] for h in chain
            },
        }

    # --- blocks ---

    def get_block(self, block_hash: bytes, include_transactions: bool = True) -> dict:
        if not self.consensus.storage.headers.has(block_hash):
            raise RpcError(f"block {block_hash.hex()} not found")
        header = self.consensus.storage.headers.get(block_hash)
        out = {
            "hash": block_hash.hex(),
            "header": {
                "version": header.version,
                "parents_by_level": [[p.hex() for p in lvl] for lvl in header.parents_by_level],
                "hash_merkle_root": header.hash_merkle_root.hex(),
                "accepted_id_merkle_root": header.accepted_id_merkle_root.hex(),
                "utxo_commitment": header.utxo_commitment.hex(),
                "timestamp": header.timestamp,
                "bits": header.bits,
                "nonce": header.nonce,
                "daa_score": header.daa_score,
                "blue_work": hex(header.blue_work),
                "blue_score": header.blue_score,
                "pruning_point": header.pruning_point.hex(),
            },
            "verbose": {
                "status": self.consensus.storage.statuses.get(block_hash),
                "is_chain_block": self.consensus.reachability.is_chain_ancestor_of(block_hash, self.consensus.sink()),
            },
        }
        if include_transactions and self.consensus.storage.block_transactions.has(block_hash):
            out["transactions"] = [self._tx_to_rpc(tx) for tx in self.consensus.storage.block_transactions.get(block_hash)]
        return out

    def get_blocks(self, low_hash: bytes | None = None, include_transactions: bool = False) -> list[dict]:
        """Blocks in the future of `low_hash` (inclusive), or all blocks."""
        hashes = list(self.consensus.storage.headers._headers)
        if low_hash is not None:
            if not self.consensus.storage.headers.has(low_hash):
                raise RpcError(f"block {low_hash.hex()} not found")
            hashes = [h for h in hashes if self.consensus.reachability.is_dag_ancestor_of(low_hash, h)]
        return [self.get_block(h, include_transactions) for h in hashes]

    def submit_block(self, block: Block) -> str:
        try:
            status = self.consensus.validate_and_insert_block(block)
        except RuleError as e:
            raise RpcError(f"block rejected: {e}") from e
        self.mining.handle_new_block_transactions(block.transactions, self.consensus.get_virtual_daa_score())
        return status

    def get_block_template(self, pay_address: str, extra_data: bytes = b"") -> Block:
        from kaspa_tpu.consensus.processes.coinbase import MinerData

        addr = Address.from_string(pay_address)
        spk = pay_to_address_script(addr)
        return self.mining.get_block_template(MinerData(spk, extra_data))

    # --- transactions ---

    def submit_transaction(self, tx) -> bytes:
        from kaspa_tpu.consensus.processes.transaction_validator import TxRuleError

        try:
            self.mining.validate_and_insert_transaction(tx)
        except (MempoolError, TxRuleError) as e:
            raise RpcError(f"transaction rejected: {e}") from e
        return tx.id()

    def get_mempool_entries(self) -> list[dict]:
        return [
            {"transaction_id": txid.hex(), "fee": e.fee, "mass": e.mass}
            for txid, e in self.mining.mempool.pool.items()
        ]

    def get_mempool_entry(self, txid: bytes) -> dict:
        e = self.mining.mempool.get(txid)
        if e is None:
            raise RpcError(f"transaction {txid.hex()} not in mempool")
        return {"transaction_id": txid.hex(), "fee": e.fee, "mass": e.mass}

    # --- utxos / balances (utxoindex-backed, rpc.rs get_utxos_by_addresses) ---

    def _require_index(self):
        if self.utxoindex is None:
            raise RpcError("method unavailable without --utxoindex")
        return self.utxoindex

    def get_utxos_by_addresses(self, addresses: list[str]) -> list[dict]:
        self._require_index()
        out = []
        for s in addresses:
            addr = Address.from_string(s)
            spk = pay_to_address_script(addr)
            for outpoint, entry in self.utxoindex.get_utxos_by_script(spk.script).items():
                out.append(
                    {
                        "address": s,
                        "outpoint": {"transaction_id": outpoint.transaction_id.hex(), "index": outpoint.index},
                        "utxo_entry": {
                            "amount": entry.amount,
                            "block_daa_score": entry.block_daa_score,
                            "is_coinbase": entry.is_coinbase,
                        },
                    }
                )
        return out

    def get_balance_by_address(self, address: str) -> int:
        spk = pay_to_address_script(Address.from_string(address))
        return self._require_index().get_balance_by_script(spk.script)

    def get_coin_supply(self) -> dict:
        return {"circulating_sompi": self._require_index().get_circulating_supply()}

    # --- subscriptions (notify_* RPCs) ---

    def register_listener(self, callback) -> int:
        return self.notifier.register(callback)

    def start_notify(self, listener_id: int, event_type: str, addresses: list[str] | None = None) -> None:
        spks = None
        if addresses is not None:
            spks = {pay_to_address_script(Address.from_string(a)).script for a in addresses}
        self.notifier.start_notify(listener_id, event_type, spks)

    def stop_notify(self, listener_id: int, event_type: str) -> None:
        self.notifier.stop_notify(listener_id, event_type)

    # --- metrics (rpc.rs get_metrics -> metrics/core MetricsSnapshot) ---

    def get_metrics(self) -> dict:
        from dataclasses import asdict

        sc = self.consensus.transaction_validator.sig_cache
        return {
            "uptime_seconds": time.time() - self.start_time,
            "block_count": len(self.consensus.storage.headers._headers) - 1,
            "tip_count": len(self.consensus.tips),
            "mempool_size": len(self.mining.mempool),
            "virtual_daa_score": self.consensus.get_virtual_daa_score(),
            "sig_cache_hits": sc.hits,
            "sig_cache_misses": sc.misses,
            "process_counters": asdict(self.consensus.counters.snapshot()),
            "process_metrics": asdict(self.perf_monitor.sample()),
        }

    # --- helpers ---

    def _tx_to_rpc(self, tx) -> dict:
        d = {
            "transaction_id": tx.id().hex(),
            "version": tx.version,
            "lock_time": tx.lock_time,
            "gas": tx.gas,
            "payload": tx.payload.hex(),
            "inputs": [
                {
                    "previous_outpoint": {
                        "transaction_id": i.previous_outpoint.transaction_id.hex(),
                        "index": i.previous_outpoint.index,
                    },
                    "signature_script": i.signature_script.hex(),
                    "sequence": i.sequence,
                }
                for i in tx.inputs
            ],
            "outputs": [],
        }
        for o in tx.outputs:
            entry = {"amount": o.value, "script_public_key": o.script_public_key.script.hex()}
            try:
                entry["address"] = extract_script_pub_key_address(o.script_public_key, self.address_prefix).to_string()
            except Exception:
                pass
            d["outputs"].append(entry)
        return d
