"""wRPC: WebSocket JSON-RPC transport over RpcCoreService.

Reference: rpc/wrpc/server/src/{server,service}.rs — the WebSocket RPC
stack (Borsh and JSON encodings) binding the same RpcApi the gRPC stack
serves.  This module implements the JSON encoding end-to-end on a
hand-rolled RFC 6455 server (no external deps): HTTP upgrade handshake,
masked client frames, text frames both ways, ping/pong, close.  Requests
reuse the daemon's dispatch table; `subscribe`/`unsubscribe` stream
notifications on the same connection exactly like the line-JSON transport
(per-connection bounded queue + writer thread, notify/src/broadcaster.rs
role).

Wire messages (JSON text frames):
  -> {"id": 1, "method": "getBlockDagInfo", "params": {}}
  <- {"id": 1, "result": {...}}
  <- {"notification": {"event": "block-added", "data": {...}}}
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import queue
import socket
import socketserver
import struct
import threading

from kaspa_tpu.utils.sync import ranked_lock

from kaspa_tpu.core.log import get_logger

log = get_logger("wrpc")

_WS_GUID = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def accept_key(client_key: str) -> str:
    return base64.b64encode(hashlib.sha1(client_key.encode() + _WS_GUID).digest()).decode()


def encode_frame(opcode: int, payload: bytes, mask: bool = False) -> bytes:
    """One complete frame (FIN set).  Servers send unmasked (RFC 6455 §5.1);
    clients must mask."""
    head = bytes([0x80 | opcode])
    mbit = 0x80 if mask else 0
    n = len(payload)
    if n < 126:
        head += bytes([mbit | n])
    elif n < 1 << 16:
        head += bytes([mbit | 126]) + struct.pack(">H", n)
    else:
        head += bytes([mbit | 127]) + struct.pack(">Q", n)
    if mask:
        key = os.urandom(4)
        masked = bytes(b ^ key[i % 4] for i, b in enumerate(payload))
        return head + key + masked
    return head + payload


MAX_MESSAGE_BYTES = 16 * 1024 * 1024  # wrpc server message cap


def _unmask(payload: bytes, key: bytes) -> bytes:
    if not payload:
        return payload
    n = len(payload)
    m = (key * (n // 4 + 1))[:n]
    return (int.from_bytes(payload, "little") ^ int.from_bytes(m, "little")).to_bytes(n, "little")


def read_frame(read_exactly) -> tuple[int, bytes, bool]:
    """Returns (opcode, payload, fin); raises ConnectionError on EOF and
    ValueError when the declared length exceeds MAX_MESSAGE_BYTES."""
    b0, b1 = read_exactly(2)
    fin = bool(b0 & 0x80)
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    n = b1 & 0x7F
    if n == 126:
        (n,) = struct.unpack(">H", read_exactly(2))
    elif n == 127:
        (n,) = struct.unpack(">Q", read_exactly(8))
    if n > MAX_MESSAGE_BYTES:
        raise ValueError(f"frame of {n} bytes exceeds the {MAX_MESSAGE_BYTES} cap")
    key = read_exactly(4) if masked else None
    payload = read_exactly(n) if n else b""
    if key:
        payload = _unmask(payload, key)
    return opcode, payload, fin


def read_message(read_exactly, on_ping=None) -> tuple[int, bytes]:
    """One complete message: assembles continuation frames until FIN
    (RFC 6455 §5.4).  Control frames interleaved mid-assembly are handled
    in place: close surfaces immediately, pings invoke ``on_ping(payload)``
    (callers answer with a pong per §5.5.2), pongs are dropped."""
    opcode, payload, fin = read_frame(read_exactly)
    if opcode in (OP_CLOSE, OP_PING, OP_PONG):
        return opcode, payload
    parts = [payload]
    total = len(payload)
    while not fin:
        op2, chunk, fin = read_frame(read_exactly)
        if op2 in (OP_CLOSE, OP_PING, OP_PONG):
            if op2 == OP_CLOSE:
                return op2, chunk
            if op2 == OP_PING and on_ping is not None:
                on_ping(chunk)
            continue
        total += len(chunk)
        if total > MAX_MESSAGE_BYTES:
            raise ValueError("fragmented message exceeds the size cap")
        parts.append(chunk)
    return opcode, b"".join(parts)


class _WrpcHandler(socketserver.StreamRequestHandler):
    def handle(self):
        daemon = self.server.daemon  # type: ignore[attr-defined]
        # --- HTTP upgrade handshake ---
        request_line = self.rfile.readline()
        headers = {}
        while True:
            line = self.rfile.readline()
            if not line or line in (b"\r\n", b"\n"):
                break
            k, _, v = line.decode("latin1").partition(":")
            headers[k.strip().lower()] = v.strip()
        if b"GET" not in request_line or "sec-websocket-key" not in headers:
            self.wfile.write(b"HTTP/1.1 400 Bad Request\r\n\r\n")
            return
        # cross-site WebSocket hijacking guard: browsers always send Origin;
        # only EXACT local origin hosts may drive the node RPC (substring
        # checks are bypassable via localhost.evil.com); native clients
        # send no Origin at all
        origin = headers.get("origin")
        if origin is not None:
            from urllib.parse import urlsplit

            host = (urlsplit(origin).hostname or "").lower()
            if host not in ("localhost", "127.0.0.1", "::1"):
                self.wfile.write(b"HTTP/1.1 403 Forbidden\r\n\r\n")
                return
        # encoding negotiation (wrpc/server serves Borsh and JSON endpoints;
        # here one port negotiates via the WebSocket subprotocol): the first
        # recognized token offered wins and is echoed back per RFC 6455 §4.2.2
        chosen_proto = None
        encoding = "json"
        offered = [t.strip() for t in headers.get("sec-websocket-protocol", "").split(",") if t.strip()]
        for token in offered:
            if token.lower() in ("kaspa-borsh", "borsh"):
                chosen_proto, encoding = token, "borsh"
                break
            if token.lower() in ("kaspa-json", "json"):
                chosen_proto, encoding = token, "json"
                break
        proto_line = f"Sec-WebSocket-Protocol: {chosen_proto}\r\n" if chosen_proto else ""
        self.wfile.write(
            (
                "HTTP/1.1 101 Switching Protocols\r\n"
                "Upgrade: websocket\r\n"
                "Connection: Upgrade\r\n"
                f"{proto_line}"
                f"Sec-WebSocket-Accept: {accept_key(headers['sec-websocket-key'])}\r\n\r\n"
            ).encode()
        )

        from kaspa_tpu.node.daemon import ConnectionPump

        pump = ConnectionPump(daemon, self.wfile, "wrpc-writer", encoding=encoding)
        borsh_subscriber_ref = [None]  # Borsh-path serving Subscriber cell

        def read_exactly(n):
            buf = b""
            while len(buf) < n:
                chunk = self.rfile.read(n - len(buf))
                if not chunk:
                    raise ConnectionError("peer closed")
                buf += chunk
            return buf

        try:
            while not pump.stop.is_set():
                try:
                    opcode, payload = read_message(
                        read_exactly,
                        on_ping=lambda p: pump.send(encode_frame(OP_PONG, p)),
                    )
                except (ConnectionError, OSError, ValueError):
                    return
                if opcode == OP_CLOSE:
                    pump.send(encode_frame(OP_CLOSE, payload[:2]))
                    return
                if opcode == OP_PING:
                    pump.send(encode_frame(OP_PONG, payload))
                    continue
                if opcode not in (OP_TEXT, OP_BINARY):
                    continue
                if opcode == OP_BINARY:
                    # Borsh encoding rides binary frames; JSON rides text
                    # (the reference serves the two encodings on separate
                    # ports — one socket, frame-typed, here)
                    from kaspa_tpu.node.daemon import _RPC_BY_ENCODING
                    from kaspa_tpu.rpc import borsh_codec

                    _RPC_BY_ENCODING.inc("borsh")
                    resp = borsh_codec.handle_frame(
                        daemon,
                        payload,
                        notification_sink=_WsBinaryAdapter(pump.outq),
                        subscriber_ref=borsh_subscriber_ref,
                        stop=pump.stop,
                    )
                    pump.send(encode_frame(OP_BINARY, resp))
                    continue
                line = pump.handle_request(payload, notification_sink=_WsQueueAdapter(pump.outq))
                pump.send(encode_frame(OP_TEXT, line.rstrip(b"\n")))
        finally:
            sub = borsh_subscriber_ref[0]
            if sub is not None:
                borsh_subscriber_ref[0] = None
                with daemon._dispatch_lock:
                    daemon.broadcaster.unregister(sub)
                sub.close()  # join the sender thread outside the lock
            pump.close()


class _WsBinaryAdapter:
    """Wraps Borsh notification frames (bytes, or zero-arg thunks evaluated
    lazily on the writer thread) into WebSocket binary frames on the shared
    outbound queue.  ``put`` is the serving Subscriber's blocking sink
    contract (raises queue.Full on timeout so socket backpressure reaches
    the subscriber queue and its overflow policy)."""

    def __init__(self, outq: queue.Queue):
        self._outq = outq

    @staticmethod
    def _wrap(frame):
        if callable(frame):
            return lambda _f=frame: encode_frame(OP_BINARY, _f())
        return encode_frame(OP_BINARY, frame)

    def put_nowait(self, frame) -> None:
        self._outq.put_nowait(self._wrap(frame))

    def put(self, frame, timeout: float | None = None) -> None:
        self._outq.put(self._wrap(frame), timeout=timeout)


class _WsQueueAdapter:
    """Adapts the daemon's line-oriented notification enqueue (bytes ending
    in newline) into WebSocket text frames on the shared outbound queue.
    ``put`` blocks (and raises queue.Full on timeout) — the serving
    Subscriber's sink contract."""

    def __init__(self, outq: queue.Queue):
        self._outq = outq

    def put_nowait(self, line: bytes) -> None:
        self._outq.put_nowait(encode_frame(OP_TEXT, line.rstrip(b"\n")))

    def put(self, line: bytes, timeout: float | None = None) -> None:
        self._outq.put(encode_frame(OP_TEXT, line.rstrip(b"\n")), timeout=timeout)


class WrpcServer:
    """WebSocket RPC front end (wrpc/server/src/server.rs)."""

    def __init__(self, daemon, host: str = "127.0.0.1", port: int = 0):
        srv = socketserver.ThreadingTCPServer((host, port), _WrpcHandler, bind_and_activate=False)
        srv.allow_reuse_address = True
        srv.daemon_threads = True
        srv.server_bind()
        srv.server_activate()
        srv.daemon = daemon  # type: ignore[attr-defined]
        self._srv = srv
        self.address = f"{host}:{srv.server_address[1]}"
        self._thread = threading.Thread(target=srv.serve_forever, daemon=True, name="wrpc-accept")

    def start(self) -> str:
        self._thread.start()
        log.info("wRPC (WebSocket JSON) listening on %s", self.address)
        return self.address

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


class WrpcClient:
    """Minimal WebSocket JSON-RPC client (wrpc/client): id-matched calls +
    streamed notifications in a queue."""

    def __init__(self, addr: str, timeout: float = 30.0, encoding: str | None = None):
        host, port = addr.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=timeout)
        self._timeout = timeout
        self.encoding = encoding
        key = base64.b64encode(os.urandom(16)).decode()
        proto_line = f"Sec-WebSocket-Protocol: kaspa-{encoding}\r\n" if encoding else ""
        self._sock.sendall(
            (
                f"GET / HTTP/1.1\r\nHost: {addr}\r\nUpgrade: websocket\r\n"
                f"Connection: Upgrade\r\nSec-WebSocket-Key: {key}\r\n"
                f"{proto_line}"
                "Sec-WebSocket-Version: 13\r\n\r\n"
            ).encode()
        )
        status = self._read_line()
        if b"101" not in status:
            raise ConnectionError(f"websocket upgrade refused: {status!r}")
        accept = None
        echoed_proto = None
        while True:
            line = self._read_line()
            if line in (b"\r\n", b"\n", b""):
                break
            if line.lower().startswith(b"sec-websocket-accept:"):
                accept = line.split(b":", 1)[1].strip().decode()
            if line.lower().startswith(b"sec-websocket-protocol:"):
                echoed_proto = line.split(b":", 1)[1].strip().decode()
        if accept != accept_key(key):
            raise ConnectionError("bad Sec-WebSocket-Accept")
        if encoding and echoed_proto != f"kaspa-{encoding}":
            raise ConnectionError(f"server did not accept the {encoding!r} encoding (echoed {echoed_proto!r})")
        self._responses: dict = {}  # id -> response (reader fills)
        self._response_cv = threading.Condition()  # graftlint: allow(raw-lock) -- client-side test helper; single condvar, no lock nesting in the process under test
        self._closed = False
        # graftlint: allow(unbounded-queue) -- client-side test helper; lives for one scripted exchange
        self.notifications: queue.Queue = queue.Queue()
        self.borsh_notifications: queue.Queue = queue.Queue()  # graftlint: allow(unbounded-queue) -- client-side test helper; lives for one scripted exchange
        self._next_id = 0
        self._id_lock = ranked_lock("wrpc.ids")
        self._reader = threading.Thread(target=self._read_loop, daemon=True, name="wrpc-client-reader")
        self._reader.start()

    def _read_line(self) -> bytes:
        out = b""
        while not out.endswith(b"\n"):
            c = self._sock.recv(1)
            if not c:
                return out
            out += c
        return out

    def _read_exactly(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("closed")
            buf += chunk
        return buf

    def _read_loop(self):
        try:
            while True:
                opcode, payload = read_message(
                    self._read_exactly,
                    on_ping=lambda p: self._sock.sendall(encode_frame(OP_PONG, p, mask=True)),
                )
                if opcode == OP_CLOSE:
                    break
                if opcode == OP_PING:
                    self._sock.sendall(encode_frame(OP_PONG, payload, mask=True))
                    continue
                if opcode not in (OP_TEXT, OP_BINARY):
                    continue
                if opcode == OP_BINARY:
                    # Borsh frames: notifications to their queue, responses
                    # keyed by the frame id
                    from kaspa_tpu.rpc import borsh_codec

                    kind, msg_id, op, r = borsh_codec.decode_frame(payload)
                    if kind == borsh_codec.KIND_NOTIFICATION:
                        self.borsh_notifications.put((op, r.read()))
                    else:
                        with self._response_cv:
                            self._responses[("borsh", msg_id)] = (kind, op, r.read())
                            self._response_cv.notify_all()
                    continue
                msg = json.loads(payload)
                if "notification" in msg:
                    n = msg["notification"]
                    self.notifications.put((n["event"], n["data"]))
                else:
                    with self._response_cv:
                        self._responses[msg.get("id")] = msg
                        self._response_cv.notify_all()
        except (OSError, ValueError, ConnectionError, EOFError, struct.error):
            pass
        with self._response_cv:
            self._closed = True
            self._response_cv.notify_all()

    def call(self, method: str, params: dict | None = None):
        import time as _time

        with self._id_lock:
            self._next_id += 1
            req_id = self._next_id
        frame = encode_frame(
            OP_TEXT, json.dumps({"id": req_id, "method": method, "params": params or {}}).encode(), mask=True
        )
        self._sock.sendall(frame)
        deadline = _time.monotonic() + self._timeout
        with self._response_cv:
            while req_id not in self._responses:
                if self._closed:
                    raise ConnectionError("connection closed")
                remaining = deadline - _time.monotonic()
                if remaining <= 0 or not self._response_cv.wait(timeout=remaining):
                    raise TimeoutError(f"wrpc call {method} timed out")
            resp = self._responses.pop(req_id)
        if "error" in resp:
            raise RuntimeError(resp["error"])
        return resp["result"]

    def call_borsh(self, op: int, payload: bytes = b""):
        """One Borsh-encoded request; returns the raw response payload
        bytes (raises on a KIND_ERROR frame)."""
        import time as _time

        from kaspa_tpu.rpc import borsh_codec

        with self._id_lock:
            self._next_id += 1
            req_id = self._next_id
        frame = borsh_codec.encode_frame(borsh_codec.KIND_REQUEST, op, payload, req_id)
        self._sock.sendall(encode_frame(OP_BINARY, frame, mask=True))
        deadline = _time.monotonic() + self._timeout
        key = ("borsh", req_id)
        with self._response_cv:
            while key not in self._responses:
                if self._closed:
                    raise ConnectionError("connection closed")
                remaining = deadline - _time.monotonic()
                if remaining <= 0 or not self._response_cv.wait(timeout=remaining):
                    raise TimeoutError(f"borsh call op={op} timed out")
            kind, _op, body = self._responses.pop(key)
        if kind == borsh_codec.KIND_ERROR:
            import io as _io

            raise RuntimeError(borsh_codec.r_string(_io.BytesIO(body)))
        return body

    def subscribe(self, event: str, addresses: list[str] | None = None):
        params = {"event": event}
        if addresses:
            params["addresses"] = addresses
        return self.call("subscribe", params)

    def subscribe_borsh(self, event_op: int, addresses: list[str] | None = None):
        """Borsh-encoded subscribe; notifications land in
        ``self.borsh_notifications`` as (op, payload bytes)."""
        import io as _io

        from kaspa_tpu.rpc import borsh_codec

        w = _io.BytesIO()
        borsh_codec.encode_subscribe_request(w, event_op, addresses)
        return self.call_borsh(borsh_codec.OP_SUBSCRIBE, w.getvalue())

    def next_notification(self, timeout: float = 30.0):
        return self.notifications.get(timeout=timeout)

    def close(self):
        try:
            self._sock.sendall(encode_frame(OP_CLOSE, b"", mask=True))
        except OSError:
            pass
        self._sock.close()
