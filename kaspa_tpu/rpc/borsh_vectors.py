"""Deterministic sample payloads for the Borsh wRPC golden vectors.

Mirrors kaspa_tpu.p2p.proto.vectors: one builder producing every serving
frame from fixed inputs, consumed by tools/gen_borsh_fixtures.py (writes
tests/fixtures/borsh/) and by the pinning test that asserts the on-disk
bytes never drift without an intentional regeneration.
"""

from __future__ import annotations

import io

from kaspa_tpu.consensus.model import ScriptPublicKey, TransactionOutpoint, UtxoEntry
from kaspa_tpu.notify.notifier import Notification
from kaspa_tpu.rpc import borsh_codec as bc

# a standard p2pk script (so address recovery has an address to recover)
# and a deliberately nonstandard one (so the Option<address> None arm is
# exercised) — fixed bytes, never derived from anything nondeterministic
_P2PK_SCRIPT = b"\x20" + bytes(range(32)) + b"\xac"
_WEIRD_SCRIPT = b"\x51\x52\x53"
_ADDRESS_PREFIX = "kaspasim"

_OUTPOINT_A = TransactionOutpoint(bytes(range(32)), 0)
_OUTPOINT_B = TransactionOutpoint(bytes(reversed(range(32))), 7)

_ENTRY_A = UtxoEntry(50_000_000_000, ScriptPublicKey(0, _P2PK_SCRIPT), 42, True)
_ENTRY_B = UtxoEntry(123_456_789, ScriptPublicKey(0, _WEIRD_SCRIPT), 1000, False, covenant_id=b"\xee" * 32)


def _address_for(script: bytes) -> str | None:
    from kaspa_tpu.crypto.addresses import extract_script_pub_key_address

    try:
        return extract_script_pub_key_address(ScriptPublicKey(0, script), _ADDRESS_PREFIX).to_string()
    except Exception:  # noqa: BLE001 - nonstandard script: no address form
        return None


def sample_frames() -> dict[str, tuple[int, bytes]]:
    """name -> (op, payload bytes) for every serving-tier Borsh message."""
    addr_a = _address_for(_P2PK_SCRIPT)
    out: dict[str, tuple[int, bytes]] = {}

    def add(name: str, op: int, encode, *args) -> None:
        w = io.BytesIO()
        encode(w, *args)
        out[name] = (op, w.getvalue())

    add("get_utxos_by_addresses_request", bc.OP_GET_UTXOS_BY_ADDRESSES,
        bc.encode_get_utxos_by_addresses_request, [addr_a])
    add("get_utxos_by_addresses_response", bc.OP_GET_UTXOS_BY_ADDRESSES,
        bc.encode_get_utxos_by_addresses_response,
        [(addr_a, _OUTPOINT_A, _ENTRY_A), (None, _OUTPOINT_B, _ENTRY_B)])
    add("get_balance_by_address_request", bc.OP_GET_BALANCE_BY_ADDRESS,
        bc.encode_get_balance_by_address_request, addr_a)
    add("get_balance_by_address_response", bc.OP_GET_BALANCE_BY_ADDRESS,
        bc.encode_get_balance_by_address_response, 50_000_000_000)
    add("get_coin_supply_request", bc.OP_GET_COIN_SUPPLY, bc.encode_get_coin_supply_request)
    add("get_coin_supply_response", bc.OP_GET_COIN_SUPPLY,
        bc.encode_get_coin_supply_response, 21_000_000_000_000)
    add("utxos_changed_notification", bc.OP_UTXOS_CHANGED_NOTIFICATION,
        bc.encode_utxos_changed_notification,
        [(_OUTPOINT_A, _ENTRY_A)], [(_OUTPOINT_B, _ENTRY_B)], _ADDRESS_PREFIX)
    add("subscribe_block_added_request", bc.OP_SUBSCRIBE,
        bc.encode_subscribe_request, bc.OP_BLOCK_ADDED_NOTIFICATION)
    add("subscribe_utxos_changed_request", bc.OP_SUBSCRIBE,
        bc.encode_subscribe_request, bc.OP_UTXOS_CHANGED_NOTIFICATION, [addr_a])

    # one full wire frame: the notification as the serving encoder emits it
    n = Notification("utxos-changed", {"added": [(_OUTPOINT_A, _ENTRY_A)], "removed": []})
    out["utxos_changed_frame"] = (
        bc.OP_UTXOS_CHANGED_NOTIFICATION,
        bc.make_utxos_changed_frame(n, _ADDRESS_PREFIX),
    )
    return out
