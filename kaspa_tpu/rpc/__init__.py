from kaspa_tpu.rpc.service import RpcCoreService  # noqa: F401
