"""Node assembly: the kaspad-equivalent daemon.

Reference: kaspad/src/{main,daemon,args}.rs — parse args, assemble the
service stack (consensus, mining manager, utxoindex, notification chain,
RPC), and serve RPC on a socket.  The wire protocol here is line-delimited
JSON-RPC over TCP (the gRPC/wRPC codec stacks bind to the same
RpcCoreService in a later milestone); P2P connections use the in-process
flow layer and can be bridged over sockets the same way.

Run: ``python -m kaspa_tpu.node --appdir /tmp/kaspa --rpclisten 127.0.0.1:16110``
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import socketserver
import threading

from kaspa_tpu.consensus.consensus import Consensus
from kaspa_tpu.consensus.params import Params, simnet_params
from kaspa_tpu.index import UtxoIndex
from kaspa_tpu.mempool import MiningManager
from kaspa_tpu.observability.core import REGISTRY
from kaspa_tpu.p2p import Node
from kaspa_tpu.rpc import RpcCoreService
from kaspa_tpu.utils.sync import ranked_lock

# per-encoding request counters (rpc/wrpc/server metrics): line-json is the
# TCP transport, json/borsh are the WebSocket text/binary frame paths
_RPC_BY_ENCODING = REGISTRY.counter_family(
    "rpc_requests_by_encoding", "encoding", help="RPC requests served, by wire encoding"
)


class DaemonArgs(argparse.Namespace):
    pass


def parse_args(argv=None) -> DaemonArgs:
    """kaspad/src/args.rs equivalent (the subset meaningful this round)."""
    p = argparse.ArgumentParser(prog="kaspa-tpu-node", description="kaspa-tpu full node")
    p.add_argument("--appdir", default=os.path.expanduser("~/.kaspa-tpu"), help="data directory")
    p.add_argument("--rpclisten", default="127.0.0.1:16110", help="host:port for JSON-RPC")
    p.add_argument("--rpclisten-wrpc", default=None, help="host:port for the WebSocket JSON wRPC server (omit to disable)")
    p.add_argument(
        "--network", default="simnet", choices=["simnet", "mainnet", "testnet", "devnet"],
        help="network preset (real genesis for mainnet/testnet/devnet; simnet uses the fast test params)",
    )
    p.add_argument("--bps", type=int, default=2, help="simnet blocks per second")
    p.add_argument("--utxoindex", action=argparse.BooleanOptionalAction, default=True, help="maintain the UTXO index")
    p.add_argument(
        "--seed", type=int, default=None,
        help="deterministic seed for mempool template-selection sampling "
        "(byte-reproducible template choice under congestion; default: fixed internal seed)",
    )
    p.add_argument(
        "--template-debounce-ms", type=float, default=250.0,
        help="serve a stale-but-mineable cached template for up to this long "
        "after tx churn, so a tx flood costs one rebuild per window instead "
        "of one per transaction (0 = rebuild on next request)",
    )
    p.add_argument(
        "--fanout-queue", type=int, default=1024,
        help="per-subscriber bounded notification queue length (serving tier backpressure)",
    )
    p.add_argument(
        "--fanout-policy", default="drop-oldest", choices=["drop-oldest", "disconnect"],
        help="subscriber queue overflow policy: evict the oldest event, or tear the connection down",
    )
    p.add_argument("--address-prefix", default=None, help="bech32 prefix (defaults per network)")
    p.add_argument(
        "--persist",
        action=argparse.BooleanOptionalAction,
        default=True,
        help="crash-safe consensus persistence under <appdir>/consensus.db (restart resumes)",
    )
    p.add_argument("--listen", default=None, help="host:port for the P2P wire (omit to disable inbound P2P)")
    p.add_argument(
        "--p2p-proto",
        action="store_true",
        help="speak the reference-compatible protobuf/gRPC P2P wire instead of the custom frame codec "
        "(both ends of a connection must use the same wire)",
    )
    p.add_argument("--upnp", action="store_true", help="map the P2P listen port on the internet gateway via UPnP")
    p.add_argument("--stratum", default=None, help="host:port for the stratum bridge (omit to disable)")
    p.add_argument("--stratum-pay-address", default=None, help="address stratum block templates pay to")
    p.add_argument(
        "--enable-unsynced-mining", action=argparse.BooleanOptionalAction, default=None,
        help="serve block templates while unsynced (defaults on for simnet, off otherwise; args.rs enable_unsynced_mining)",
    )
    p.add_argument("--connect", action="append", default=[], help="peer host:port to dial (repeatable); IBD runs on connect")
    p.add_argument("--dnsseed", action="append", default=[], help="seed hostname[:port] resolved into the address book (repeatable)")
    def _ram_scale(v: str) -> float:
        import math

        x = float(v)
        # args.rs bounds the flag at parse time; 0/negative/inf/nan would
        # silently floor every cache or crash the policy scaler
        if not math.isfinite(x) or not (0.1 <= x <= 10.0):
            raise argparse.ArgumentTypeError("--ram-scale must be a finite value in [0.1, 10]")
        return x

    p.add_argument("--ram-scale", type=_ram_scale, default=1.0,
                   help="scale all store cache budgets, 0.1-10 (cache_policy_builder.rs --ram-scale)")
    p.add_argument(
        "--mesh", default=None, metavar="N",
        help="shard batch signature verify + muhash over N devices via shard_map "
        "(default 1 = single device; 'auto' = every visible device; "
        "CPU testing: XLA_FLAGS=--xla_force_host_platform_device_count=8)",
    )
    p.add_argument(
        "--coalesce", default=None, metavar="N",
        help="coalesce signature verify jobs across blocks into super-batches of "
        "up to N jobs before device dispatch (default off; 'auto' seeds the "
        "target from BENCH_SWEEP.json; flush age via KASPA_TPU_COALESCE_AGE_MS)",
    )
    p.add_argument(
        "--verify-mode", choices=("ladder", "aggregate", "auto"), default=None,
        help="schnorr batch-verify lane: per-signature ladders (default), the "
        "aggregated random-linear-combination multi-scalar check, or 'auto' "
        "(aggregate at/above BENCH_SWEEP.json's measured crossover batch); "
        "results are bit-identical either way",
    )
    p.add_argument(
        "--fabric", nargs="+", default=None, metavar=("MODE", "ADDR"),
        help="verify fabric: 'serve [HOST:PORT]' runs a verifyd slice server "
        "inside this node (default 127.0.0.1:18500, port 0 = ephemeral); "
        "'connect ADDR[,ADDR...]' routes batch signature verification to "
        "remote verifyd slices — least-loaded routing, per-slice breakers, "
        "bit-identical host degraded lane when every slice is down",
    )
    p.add_argument(
        "--serving-pool", type=int,
        default=int(os.environ.get("KASPA_TPU_SERVING_POOL", "0")),
        metavar="N",
        help="drain serving-tier subscribers with a shared crew of N sender "
        "threads instead of one thread per subscriber (0 = per-subscriber "
        "threads, the historical shape; the 50k-subscriber load harness "
        "runs pooled)",
    )
    p.add_argument(
        "--fanout-shards", type=int,
        default=int(os.environ.get("KASPA_TPU_FANOUT_SHARDS", "1")),
        metavar="N",
        help="partition the serving fanout across N shard workers with a "
        "scope-pushdown inverted index (subscribers hash-partitioned by "
        "connection id; 1 = the single-fanout broadcaster, bit-identical "
        "delivered streams either way; with --serving-pool the crew splits "
        "into per-shard pools)",
    )
    p.add_argument(
        "--flight", action=argparse.BooleanOptionalAction, default=False,
        help="per-block flight recorder: cross-thread span trees for every "
        "validated block in a bounded ring, served over getTraces and dumped "
        "to <appdir>/flight-*.json on demand, crash, or breaker-open "
        "(tools/trace_report.py --perfetto renders the dump)",
    )
    p.add_argument(
        "--bench-capture", action=argparse.BooleanOptionalAction, default=False,
        help="re-probe the device on the periodic tick and capture a fresh "
        "bench.py number the moment a trivial jit answers "
        "(interval via KASPA_TPU_BENCH_RECHECK_S; results in <appdir>/BENCH_CAPTURE.json)",
    )
    # consensus-parameter overrides (kaspad exposes these for testnets;
    # primarily for pruning/IBD integration tests at small scale)
    p.add_argument("--override-pruning-depth", type=int, default=None)
    p.add_argument("--override-finality-depth", type=int, default=None)
    p.add_argument("--override-merge-depth", type=int, default=None)
    p.add_argument("--override-proof-m", type=int, default=None)
    p.add_argument("--override-window-scale", type=int, default=None,
                   help="shrink difficulty/median windows to this sampled size")
    return p.parse_args(argv, namespace=DaemonArgs())


def _apply_param_overrides(params: Params, args: DaemonArgs) -> Params:
    if getattr(args, "override_pruning_depth", None):
        params.pruning_depth = args.override_pruning_depth
    if getattr(args, "override_finality_depth", None):
        params.finality_depth = args.override_finality_depth
    if getattr(args, "override_merge_depth", None):
        params.merge_depth = args.override_merge_depth
    if getattr(args, "override_proof_m", None):
        params.pruning_proof_m = args.override_proof_m
    ws = getattr(args, "override_window_scale", None)
    if ws:
        params.difficulty_window_size = ws
        params.min_difficulty_window_size = min(5, ws)
        params.difficulty_sample_rate = 2
        params.past_median_time_window_size = ws
        params.past_median_time_sample_rate = 2
    return params


def _json_notification_line(n) -> bytes:
    """Serving-tier JSON encoder: one Notification -> one wire line.  Runs
    on the subscriber's sender thread, never on the consensus thread."""
    return (
        json.dumps({"notification": {"event": n.event_type, "data": _serialize_notification(n)}}) + "\n"
    ).encode()


def _serialize_notification(n) -> dict:
    """Wire shapes for streamed notifications (rpc/grpc/server's
    notification message bodies, JSON-ified)."""
    if n.event_type == "block-added":
        blk = n.data["block"]
        return {
            "hash": blk.hash.hex(),
            "daa_score": blk.header.daa_score,
            "blue_score": blk.header.blue_score,
            "timestamp": blk.header.timestamp,
            "tx_count": len(blk.transactions),
        }
    if n.event_type == "utxos-changed":
        def pairs(key):
            return [
                {
                    "outpoint": {"transaction_id": op.transaction_id.hex(), "index": op.index},
                    "utxo_entry": {
                        "amount": e.amount,
                        "block_daa_score": e.block_daa_score,
                        "is_coinbase": e.is_coinbase,
                        "script_public_key": {
                            "version": e.script_public_key.version,
                            "script": e.script_public_key.script.hex(),
                        },
                    },
                }
                for op, e in n.data.get(key, [])
            ]

        return {"added": pairs("added"), "removed": pairs("removed")}
    if n.event_type == "new-block-template":
        return {}
    if n.event_type == "virtual-chain-changed":
        return dict(n.data)  # already JSON-shaped (hex lists + txid map)
    # score changes and the rest carry plain JSON-able payloads
    return {k: v for k, v in n.data.items() if isinstance(v, (int, str, bool, float, list))}


class ConnectionPump:
    """Per-connection outbound pump shared by every RPC transport (line-
    JSON and WebSocket): a bounded queue drained by a dedicated writer
    thread (notify/src/broadcaster.rs role) so a slow consumer can never
    stall the consensus thread publishing an event — overflow drops, never
    blocks — plus the subscription-listener lifecycle."""

    def __init__(self, daemon: "Daemon", wfile, name: str, encoding: str = "line-json"):
        import queue as _queue

        self.daemon = daemon
        self.outq: _queue.Queue = _queue.Queue(maxsize=4096)
        self.stop = threading.Event()
        self.subscriber_ref = [None]  # one serving Subscriber per connection
        self.encoding = encoding
        self._wfile = wfile
        self._queue_mod = _queue
        self._writer = threading.Thread(target=self._writer_loop, daemon=True, name=name)
        self._writer.start()

    def _writer_loop(self):
        # drain until the sentinel: queued responses still flush after
        # stop is set (half-close clients must get their last reply);
        # a dead socket or stop+empty ends the thread
        while True:
            try:
                item = self.outq.get(timeout=0.5)
            except self._queue_mod.Empty:
                if self.stop.is_set():
                    return
                continue
            if item is None:
                return
            if callable(item):
                # deferred encoding: expensive serialization (e.g. Borsh
                # full-block notifications) runs on this writer thread, not
                # on the consensus thread that published the event
                try:
                    item = item()
                except Exception:  # noqa: BLE001 - encoding failure drops the frame
                    from kaspa_tpu.core.log import get_logger

                    get_logger("rpc.pump").exception("deferred notification encoding failed")
                    continue
            try:
                self._wfile.write(item)
                self._wfile.flush()
            except (OSError, ValueError):  # ValueError: write on a closed file object
                self.stop.set()
                return

    def send(self, data: bytes) -> None:
        self.outq.put(data)

    def handle_request(self, payload: bytes, notification_sink=None) -> bytes:
        """Dispatch one JSON request; returns the encoded response line.
        ``notification_sink``: queue-like receiving notification lines
        (defaults to the raw outq — the line-JSON transport)."""
        req_id = None
        _RPC_BY_ENCODING.inc(self.encoding)
        try:
            req = json.loads(payload)
            req_id = req.get("id")
            method = req.get("method", "")
            params = req.get("params", {})
            if method in ("subscribe", "unsubscribe"):
                result = self.daemon.handle_subscription(
                    method, params, notification_sink or self.outq, self.subscriber_ref, self.stop
                )
            else:
                result = self.daemon.dispatch(method, params)
            resp = {"id": req_id, "result": result}
        except Exception as e:  # noqa: BLE001 - wire boundary
            resp = {"id": req_id, "error": str(e)}
            # stable machine-readable rejection code (RpcError.code):
            # clients branch on tx-orphan/tx-duplicate/... without parsing
            code = getattr(e, "code", None)
            if code:
                resp["error_code"] = code
            # node-overloaded brownout sheds carry a resubmission hint
            retry_ms = getattr(e, "retry_after_ms", None)
            if retry_ms:
                resp["retryAfterMs"] = int(retry_ms)
        return (json.dumps(resp) + "\n").encode()

    def close(self) -> None:
        sub = self.subscriber_ref[0]
        if sub is not None:
            self.subscriber_ref[0] = None
            with self.daemon._dispatch_lock:
                self.daemon.broadcaster.unregister(sub)
            sub.close()  # join the sender thread outside the lock
        self.stop.set()
        try:
            self.outq.put_nowait(None)
        except self._queue_mod.Full:
            pass  # writer exits via stop+empty / OSError


class _RpcHandler(socketserver.StreamRequestHandler):
    """One connection: request/response lines plus, after a `subscribe`,
    interleaved `{"notification": ...}` lines over the shared pump."""

    def handle(self):
        daemon: Daemon = self.server.daemon  # type: ignore[attr-defined]
        pump = ConnectionPump(daemon, self.wfile, "rpc-notify-writer")
        try:
            for line in self.rfile:
                line = line.strip()
                if not line:
                    continue
                pump.send(pump.handle_request(line))
        finally:
            pump.close()


DB_VERSION = 1
# version -> upgrade fn(engine) bringing a DB from `version` to `version+1`
# (daemon.rs:441-522 upgrade machinery; populated as formats evolve)
DB_UPGRADES: dict = {}

_NETWORK_PREFIX = {"simnet": "kaspasim", "mainnet": "kaspa", "testnet": "kaspatest", "devnet": "kaspadev"}


def _network_params_for(args: DaemonArgs) -> Params:
    if args.network == "simnet":
        return simnet_params(bps=args.bps)
    from kaspa_tpu.consensus import networks

    return {
        "mainnet": networks.mainnet_params,
        "testnet": networks.testnet_params,
        "devnet": networks.devnet_params,
    }[args.network]()


class Daemon:
    """create_core_with_runtime equivalent: wire every service together."""

    def __init__(self, args: DaemonArgs, params: Params | None = None):
        self.args = args
        os.makedirs(args.appdir, exist_ok=True)
        if getattr(args, "address_prefix", None) is None:
            args.address_prefix = _NETWORK_PREFIX.get(args.network, "kaspasim")
        self.params = _apply_param_overrides(
            params if params is not None else _network_params_for(args), args
        )
        from kaspa_tpu.ops import dispatch as verify_dispatch
        from kaspa_tpu.ops import mesh as mesh_dispatch

        # process-wide: every batch verify/muhash call in this daemon routes
        # through the mesh once configured (> 1)
        self.mesh_size = mesh_dispatch.configure(getattr(args, "mesh", None))
        # process-wide: verify jobs coalesce across blocks/callers into
        # super-batches once configured (> 0); mesh must resolve first so
        # 'auto' picks the sweep's best batch for the active mesh size
        self.coalesce_target = verify_dispatch.configure(getattr(args, "coalesce", None))
        # process-wide: which schnorr batch-verify lane dispatch resolves to
        # (ladder / aggregate / auto-by-crossover); bit-identical either way
        if getattr(args, "verify_mode", None) is not None:
            verify_dispatch.set_verify_mode(args.verify_mode)
        fab = getattr(args, "fabric", None) or []
        self.fabric_mode = fab[0] if fab else None
        if self.fabric_mode not in (None, "serve", "connect"):
            raise SystemExit(f"--fabric mode must be serve|connect, got {self.fabric_mode!r}")
        if self.fabric_mode == "connect" and len(fab) < 2:
            raise SystemExit("--fabric connect requires ADDR[,ADDR...]")
        self._fabric_arg = fab[1] if len(fab) > 1 else None
        self.fabric_service = None
        self.fabric_addr = None
        if getattr(args, "flight", False):
            from kaspa_tpu.observability import flight

            # breaker-open and crash paths dump into the appdir unprompted;
            # getTraces serves the live ring
            flight.enable(dump_dir=args.appdir)
        self.db = None
        if getattr(args, "persist", False):
            from kaspa_tpu.storage.kv import KvStore

            # ACTIVE meta file points at the live db (staging swaps rotate it)
            active = "consensus.db"
            active_path = os.path.join(args.appdir, "ACTIVE")
            if os.path.exists(active_path):
                with open(active_path) as f:
                    name = f.read().strip()
                # a truncated pointer (crash mid-replace) must not silently
                # reset to genesis: only honor names whose db file exists
                if name and os.path.exists(os.path.join(args.appdir, name)):
                    active = name
            # retire staging leftovers from aborted swaps
            for fn in os.listdir(args.appdir):
                if fn.startswith("consensus-staging-") and fn != active:
                    try:
                        os.remove(os.path.join(args.appdir, fn))
                    except OSError:
                        pass
            self.db = KvStore(os.path.join(args.appdir, active))
            self._check_db_version(self.db)
        from kaspa_tpu.consensus.stores import CachePolicy

        self.cache_policy = CachePolicy().scaled(getattr(args, "ram_scale", 1.0))
        self.consensus = Consensus(self.params, db=self.db, cache_policy=self.cache_policy)
        self.node = Node(
            self.consensus,
            name="daemon",
            mempool_seed=getattr(args, "seed", None),
            template_debounce=getattr(args, "template_debounce_ms", 0.0) / 1000.0,
        )
        self.node.cmgr._factory = self._staging_factory
        self.node.cmgr.on_swap(self._on_consensus_swap)
        self.mining = self.node.mining
        import itertools

        self._fanout_queue = getattr(args, "fanout_queue", None) or 1024
        self._fanout_policy = getattr(args, "fanout_policy", None) or "drop-oldest"
        # shared sender crew (--serving-pool / KASPA_TPU_SERVING_POOL):
        # None keeps the historical thread-per-subscriber shape.  With
        # --fanout-shards > 1 the crew is owned per shard instead (the
        # ShardedBroadcaster builds one pool per shard from the same
        # worker budget), so no shared pool is created here.
        pool_workers = int(getattr(args, "serving_pool", 0) or 0)
        self._fanout_shards = max(1, int(getattr(args, "fanout_shards", 1) or 1))
        if pool_workers > 0 and self._fanout_shards <= 1:
            from kaspa_tpu.serving import SenderPool

            self.serving_pool = SenderPool(workers=pool_workers)
        else:
            self.serving_pool = None
        self._serving_pool_workers = pool_workers
        self._sub_seq = itertools.count(1)
        self.utxoindex = self._make_utxoindex(self.consensus) if args.utxoindex else None
        from kaspa_tpu.p2p.address_manager import AddressManager, ConnectionManager

        self.address_manager = AddressManager(seed=getattr(args, "seed", None))
        self.connection_manager = ConnectionManager(
            self.node, self.address_manager, tick_seconds=5.0, seed=getattr(args, "seed", None)
        )
        self.node.address_manager = self.address_manager
        self.rpc = RpcCoreService(
            self.consensus,
            self.mining,
            self.utxoindex,
            args.address_prefix,
            p2p_node=self.node,
            address_manager=self.address_manager,
            connection_manager=self.connection_manager,
            shutdown_fn=lambda: threading.Thread(target=self.stop, daemon=True).start(),
        )
        # serving tier: the async fanout stage between the rpc notifier and
        # every remote subscriber.  Bound to the notifier OBJECT, which
        # survives consensus staging swaps via rebind_parent, so the
        # broadcaster (and its wildcard listener id) lives daemon-long.
        # --fanout-shards N > 1 swaps in the subscriber-partitioned tier
        # behind the same surface (bit-identical delivered streams).
        from kaspa_tpu.serving.broadcaster import tune_gil_switch_interval

        tune_gil_switch_interval()
        if self._fanout_shards > 1:
            from kaspa_tpu.serving import ShardedBroadcaster

            per_shard = (
                max(1, -(-self._serving_pool_workers // self._fanout_shards))
                if self._serving_pool_workers > 0
                else 0
            )
            self.broadcaster = ShardedBroadcaster(
                self.rpc.notifier,
                shards=self._fanout_shards,
                pool_workers=per_shard,
            )
        else:
            from kaspa_tpu.serving import Broadcaster

            self.broadcaster = Broadcaster(self.rpc.notifier)
        # node-wide overload-control plane (resilience/overload.py): samples
        # pressure on its own ticker, engages brownout actions through the
        # subsystem seams.  The mining facade is rebuilt on consensus
        # staging swaps, so signals/actions reach it through a live proxy
        # instead of capturing the bootstrap instance.
        from kaspa_tpu.resilience.overload import build_controller

        daemon_self = self

        class _MiningProxy:
            @property
            def mempool(self):
                return daemon_self.node.mining.mempool

            def set_template_deferral(self, grace_s: float) -> None:
                daemon_self.node.mining.set_template_deferral(grace_s)

        self.overload = build_controller(
            mining=_MiningProxy(),
            tier=self.node.ingest,
            broadcaster=self.broadcaster,
            node=self.node,
        )
        from kaspa_tpu.mining import MiningRuleEngine

        allow_unsynced = getattr(args, "enable_unsynced_mining", None)
        if allow_unsynced is None:
            allow_unsynced = args.network == "simnet"
        self.rule_engine = MiningRuleEngine(
            lambda: self.consensus, self.params, lambda: bool(self.node.peers),
            allow_unsynced=allow_unsynced,
        )
        self.rpc.rule_engine = self.rule_engine
        # consensus/mempool objects are single-writer: RPC dispatch and P2P
        # reader threads all serialize through the node lock (the reference
        # takes consensus sessions; an RW split can come later)
        self._dispatch_lock = self.node.lock
        self._server: socketserver.ThreadingTCPServer | None = None
        self._thread: threading.Thread | None = None
        self.p2p_server = None
        self.p2p_wire = "proto" if getattr(args, "p2p_proto", False) else "custom"

        # service runtime (core/src/core.rs): ordered start, reverse-order
        # stop, periodic metrics sampling on the tick service
        from kaspa_tpu.core import Core, TickService
        from kaspa_tpu.core.log import get_logger
        from kaspa_tpu.core.service import CallbackService
        from kaspa_tpu.metrics.core import MetricsData, collect_snapshot
        from kaspa_tpu.metrics.perf_monitor import PerfMonitor

        self.log = get_logger("daemon")
        if self.mesh_size > 1:
            self.log.info("mesh dispatch enabled over %d devices", self.mesh_size)
        if self.coalesce_target:
            self.log.info("verify coalescing enabled, super-batch target %d", self.coalesce_target)
        if verify_dispatch.verify_mode() != "ladder":
            self.log.info("schnorr verify mode: %s", verify_dispatch.verify_mode())
        self.core = Core()
        self.perf_monitor = PerfMonitor()
        self.metrics_data = MetricsData()
        self.tick = TickService()

        # prometheus text rendered on the metrics tick (not per scrape):
        # rendering walks the whole registry, so it rides the existing
        # 10s cadence and getMetricsPrometheus serves the cached page
        self.prom_text = ""

        def sample_metrics():
            with self._dispatch_lock:
                self.metrics_data.push(
                    collect_snapshot(self.consensus, self.mining, self.perf_monitor, p2p_node=self.node)
                )
                # piggyback cache hygiene on the metrics cadence: drops the
                # pruning-point SMT snapshot once stale (anchor moved or idle)
                self.node.prune_caches()
            from kaspa_tpu.observability import prom

            self.prom_text = prom.render()

        self.tick.register(10.0, sample_metrics)

        # recurring-timer bench capture (ROADMAP item 1): re-probe the
        # device on the metrics cadence, run the full bench the moment a
        # trivial jit answers, keep the best number in the appdir
        self.bench_capture = None
        if getattr(args, "bench_capture", False):
            from kaspa_tpu.node.bench_capture import BenchCapture

            self.bench_capture = BenchCapture(args.appdir, logger=self.log)
            self.tick.register(10.0, self.bench_capture.tick)

        def sample_rule_engine():
            with self._dispatch_lock:
                self.rule_engine.sample()

        from kaspa_tpu.mining.rule_engine import SNAPSHOT_INTERVAL

        self.tick.register(float(SNAPSHOT_INTERVAL), sample_rule_engine)
        self.rpc.metrics_provider = lambda: self.metrics_data.last
        self.core.bind(self.tick)
        self.core.bind(CallbackService("rpc-server", on_start=self._start_rpc_service, on_stop=self._stop_rpc_service))
        self.core.bind(CallbackService("p2p-server", on_start=self._start_p2p_service, on_stop=self._stop_p2p_service))
        if self.fabric_mode:
            self.core.bind(
                CallbackService("fabric", on_start=self._start_fabric_service, on_stop=self._stop_fabric_service)
            )
        self.wrpc_server = None
        if getattr(args, "rpclisten_wrpc", None):
            self.core.bind(
                CallbackService("wrpc-server", on_start=self._start_wrpc_service, on_stop=self._stop_wrpc_service)
            )
        self.stratum_server = None
        if getattr(args, "stratum", None):
            self.core.bind(
                CallbackService("stratum", on_start=self._start_stratum_service, on_stop=self._stop_stratum_service)
            )

    def _check_db_version(self, db) -> None:
        """Stamp fresh DBs; refuse (or upgrade, when a hook exists) stale
        ones instead of silently misreading a foreign format
        (daemon.rs:441-522)."""
        key = b"MTdb_version"
        net_key = b"MTdb_network"
        raw = db.engine.get(key)
        if raw is None:
            if len(db.engine) > 0:
                raise SystemExit(
                    "consensus DB has no version stamp (pre-versioning format); "
                    "delete the datadir or run the DB tooling to migrate"
                )
            db.engine.put(key, str(DB_VERSION).encode())
            db.engine.put(net_key, self.params.name.encode())
            return
        stamped_net = (db.engine.get(net_key) or b"").decode()
        if stamped_net and stamped_net != self.params.name:
            raise SystemExit(
                f"consensus DB belongs to network {stamped_net!r}, not {self.params.name!r}; "
                "use a separate --appdir per network"
            )
        version = int(raw)
        while version < DB_VERSION:
            upgrade = DB_UPGRADES.get(version)
            if upgrade is None:
                raise SystemExit(
                    f"consensus DB version {version} is older than {DB_VERSION} "
                    "and no upgrade path exists; delete the datadir to resync"
                )
            upgrade(db.engine)
            version += 1
            db.engine.put(key, str(version).encode())
        if version > DB_VERSION:
            raise SystemExit(
                f"consensus DB version {version} is newer than this binary supports ({DB_VERSION})"
            )

    def _make_utxoindex(self, consensus) -> UtxoIndex:
        """Persistent (journaled KV under <appdir>/utxoindex.db) when the
        node persists; the in-memory index otherwise."""
        db_path = None
        if getattr(self.args, "persist", False):
            db_path = os.path.join(self.args.appdir, "utxoindex.db")
        return UtxoIndex(consensus, db_path=db_path)

    # --- serving-tier subscribers (one per connection, lazily created) ---

    def _subscriber_placement(self, name: str):
        """(pool, shard) a new subscriber must be built with: its shard's
        sender crew under --fanout-shards, the shared pool (or None)
        otherwise."""
        bc = self.broadcaster
        if bc is not None and hasattr(bc, "sender_pool_for"):
            return bc.sender_pool_for(name), bc.shard_of(name)
        return self.serving_pool, None

    def make_json_subscriber(self, sink, stop=None):
        from kaspa_tpu.serving import Subscriber

        name = f"json-{next(self._sub_seq)}"
        pool, shard = self._subscriber_placement(name)
        return Subscriber(
            name,
            _json_notification_line,
            sink,
            encoding="json",
            maxlen=self._fanout_queue,
            policy=self._fanout_policy,
            on_disconnect=stop.set if stop is not None else None,
            pool=pool,
            shard=shard,
        )

    def make_borsh_subscriber(self, sink, stop=None):
        from kaspa_tpu.rpc import borsh_codec
        from kaspa_tpu.serving import Subscriber

        prefix = self.args.address_prefix
        name = f"borsh-{next(self._sub_seq)}"
        pool, shard = self._subscriber_placement(name)
        return Subscriber(
            name,
            lambda n: borsh_codec.encode_notification(n, prefix),
            sink,
            encoding="borsh",
            maxlen=self._fanout_queue,
            policy=self._fanout_policy,
            on_disconnect=stop.set if stop is not None else None,
            pool=pool,
            shard=shard,
        )

    # --- staging consensus (proof IBD) ---

    def _staging_factory(self):
        db = None
        if getattr(self.args, "persist", False):
            import time as _time

            from kaspa_tpu.storage.kv import KvStore

            self._staging_db_name = f"consensus-staging-{int(_time.time() * 1000)}.db"
            db = KvStore(os.path.join(self.args.appdir, self._staging_db_name))
        return Consensus(self.params, db=db, cache_policy=self.cache_policy)

    def _on_consensus_swap(self, new_consensus) -> None:
        """Rebind every consensus-holding service after a staging commit
        (Node already rebuilt its MiningManager)."""
        old_db = self.db
        old_notifier = self.rpc.notifier
        self.consensus = new_consensus
        self.mining = self.node.mining
        if self.utxoindex is not None:
            # the persistent index owns <appdir>/utxoindex.db: close it
            # (listener + db handle) before the replacement reopens the path
            self.utxoindex.close()
        self.utxoindex = self._make_utxoindex(new_consensus) if self.args.utxoindex else None
        self.rpc = RpcCoreService(
            new_consensus,
            self.mining,
            self.utxoindex,
            self.args.address_prefix,
            p2p_node=self.node,
            address_manager=self.address_manager,
            connection_manager=self.connection_manager,
            shutdown_fn=self.rpc.shutdown_fn,
        )
        self.rpc.metrics_provider = lambda: self.metrics_data.last
        self.rpc.rule_engine = self.rule_engine
        # live wire subscriptions must survive the swap: keep the old
        # notifier object (listener ids intact) and re-chain it onto the
        # new consensus root
        old_notifier.rebind_parent(new_consensus.notification_root)
        self.rpc.notifier = old_notifier
        if new_consensus.storage.db is not None:
            # atomic pointer rotation: tmp + rename so a crash mid-write
            # cannot leave a truncated ACTIVE behind
            active_path = os.path.join(self.args.appdir, "ACTIVE")
            with open(active_path + ".tmp", "w") as f:
                f.write(self._staging_db_name)
                f.flush()
                os.fsync(f.fileno())
            os.replace(active_path + ".tmp", active_path)
            self.db = new_consensus.storage.db
        if old_db is not None and old_db is not self.db:
            old_db.close()

    # --- rpc wire dispatch ---

    _METHODS = {
        "getServerInfo": lambda rpc, p: {**rpc.get_server_info().__dict__, "coinbase_maturity": rpc.consensus.params.coinbase_maturity},
        "getBlockDagInfo": lambda rpc, p: rpc.get_block_dag_info(),
        "getBlock": lambda rpc, p: rpc.get_block(bytes.fromhex(p["hash"]), p.get("includeTransactions", True)),
        "getSinkBlueScore": lambda rpc, p: rpc.get_sink_blue_score(),
        "getVirtualChainFromBlock": lambda rpc, p: rpc.get_virtual_chain_from_block(bytes.fromhex(p["startHash"])),
        "getMempoolEntries": lambda rpc, p: rpc.get_mempool_entries(),
        "getUtxosByAddresses": lambda rpc, p: rpc.get_utxos_by_addresses(p["addresses"]),
        "getBalanceByAddress": lambda rpc, p: rpc.get_balance_by_address(p["address"]),
        "getCoinSupply": lambda rpc, p: rpc.get_coin_supply(),
        "getMetrics": lambda rpc, p: rpc.get_metrics(),
        "getMetricsPrometheus": lambda rpc, p: rpc.get_metrics_prometheus(),
        "getTraces": lambda rpc, p: rpc.get_traces(
            int(p.get("limit", 32)), bool(p.get("verbose", False))
        ),
        "ping": lambda rpc, p: rpc.ping(),
        "getCurrentNetwork": lambda rpc, p: rpc.get_current_network(),
        "getInfo": lambda rpc, p: rpc.get_info(),
        "getBlockCount": lambda rpc, p: rpc.get_block_count(),
        "getSyncStatus": lambda rpc, p: rpc.get_sync_status(),
        "getSystemInfo": lambda rpc, p: rpc.get_system_info(),
        "getSink": lambda rpc, p: rpc.get_sink().hex(),
        "getHeaders": lambda rpc, p: rpc.get_headers(
            bytes.fromhex(p["startHash"]), p.get("limit", 100), p.get("isAscending", True)
        ),
        "getCurrentBlockColor": lambda rpc, p: rpc.get_current_block_color(bytes.fromhex(p["hash"])),
        "getDaaScoreTimestampEstimate": lambda rpc, p: rpc.get_daa_score_timestamp_estimate(p["daaScores"]),
        "estimateNetworkHashesPerSecond": lambda rpc, p: rpc.estimate_network_hashes_per_second(
            p.get("windowSize", 1000),
            bytes.fromhex(p["startHash"]) if p.get("startHash") else None,
        ),
        "getBlockRewardInfo": lambda rpc, p: rpc.get_block_reward_info(
            bytes.fromhex(p["hash"]) if p.get("hash") else None
        ),
        "getFeeEstimate": lambda rpc, p: rpc.get_fee_estimate(),
        "getFeeEstimateExperimental": lambda rpc, p: rpc.get_fee_estimate_experimental(p.get("verbose", False)),
        "getBalancesByAddresses": lambda rpc, p: rpc.get_balances_by_addresses(p["addresses"]),
        "getMempoolEntriesByAddresses": lambda rpc, p: rpc.get_mempool_entries_by_addresses(p["addresses"]),
        "getConnections": lambda rpc, p: rpc.get_connections(),
        "getConnectedPeerInfo": lambda rpc, p: rpc.get_connected_peer_info(),
        "getPeerAddresses": lambda rpc, p: rpc.get_peer_addresses(),
        "addPeer": lambda rpc, p: rpc.add_peer(p["address"], p.get("isPermanent", False)),
        "ban": lambda rpc, p: rpc.ban(p["ip"]),
        "unban": lambda rpc, p: rpc.unban(p["ip"]),
        "getUtxoReturnAddress": lambda rpc, p: rpc.get_utxo_return_address(
            bytes.fromhex(p["txid"]), p.get("acceptingBlockDaaScore", 0)
        ),
    }

    def handle_subscription(self, method: str, params: dict, sink, subscriber_ref, stop) -> str:
        """subscribe/unsubscribe verbs for one connection.

        params: {"event": <EVENT_TYPES name>, "addresses": [bech32...]?}.
        The connection's serving Subscriber (bounded queue + sender thread)
        is created lazily on first subscribe and registered on the
        broadcaster; the UtxosChanged address scope is pushed down so
        filtering happens once per event at the fanout stage."""
        from kaspa_tpu.notify.notifier import EVENT_TYPES

        event = params.get("event")
        if event not in EVENT_TYPES:
            raise ValueError(f"unknown event type {event!r}")
        scripts = None
        addresses = params.get("addresses")
        if addresses:
            from kaspa_tpu.crypto.addresses import Address, pay_to_address_script

            scripts = {pay_to_address_script(Address.from_string(a)).script for a in addresses}
        with self._dispatch_lock:
            if subscriber_ref[0] is None:
                subscriber_ref[0] = self.broadcaster.register(self.make_json_subscriber(sink, stop))
            if method == "subscribe":
                self.broadcaster.subscribe(subscriber_ref[0], event, scripts)
            else:
                self.broadcaster.unsubscribe(subscriber_ref[0], event)
        return "ok"

    def dispatch(self, method: str, params: dict):
        if method == "submitTransaction":
            # deliberately NOT under the dispatch lock: admission rides the
            # batched ingest tier, whose waves take the node lock internally
            # — concurrent submitters therefore queue up and coalesce into
            # one verify wave instead of serializing one-by-one here
            from kaspa_tpu.wallet.__main__ import wire_to_tx

            tx = wire_to_tx(params["tx"])
            txid = self.rpc.submit_transaction(tx)
            return txid.hex()
        with self._dispatch_lock:
            # graftlint: allow(blocking-under-lock) -- RPC mutation path serializes consensus work by design; device round trips run under the dispatch lock deliberately
            return self._dispatch(method, params)

    def _dispatch(self, method: str, params: dict):
        if method == "getBlockTemplate":
            block = self.rpc.get_block_template(params["payAddress"], bytes.fromhex(params.get("extraData", "")))
            return {"block_hash": block.hash.hex(), "transactions": len(block.transactions)}
        if method == "submitBlockByTemplateHash":
            # in-process miner convenience: submit the cached template
            cached = self.mining.template_cache.get()
            if cached is None or cached.hash.hex() != params["hash"]:
                raise ValueError("template not cached")
            status = self.node.submit_block(cached)  # insert + unorphan + relay
            return {"status": status}
        fn = self._METHODS.get(method)
        if fn is None:
            raise ValueError(f"unknown method {method}")
        return fn(self.rpc, params)

    # --- lifecycle (core/src/core.rs run/shutdown shape) ---

    def _start_rpc_service(self, _core) -> list:
        host, port = self.args.rpclisten.rsplit(":", 1)
        srv = socketserver.ThreadingTCPServer((host, int(port)), _RpcHandler, bind_and_activate=False)
        srv.allow_reuse_address = True
        srv.daemon_threads = True
        srv.server_bind()
        srv.server_activate()
        srv.daemon = self  # type: ignore[attr-defined]
        self._server = srv
        self._thread = threading.Thread(target=srv.serve_forever, daemon=True)
        self._thread.start()
        self._rpc_addr = f"{host}:{srv.server_address[1]}"
        self.log.info("RPC listening on %s", self._rpc_addr)
        return [self._thread]

    def _stop_rpc_service(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def _start_p2p_service(self, _core) -> list:
        if getattr(self.args, "listen", None):
            from kaspa_tpu.p2p.transport import P2PServer, get_codec

            lhost, lport = self.args.listen.rsplit(":", 1)
            self.p2p_server = P2PServer(
                self.node, lhost, int(lport), address_manager=self.address_manager, codec=get_codec(self.p2p_wire)
            )
            self.p2p_server.start()
            self.node.listen_port = int(self.p2p_server.address.rsplit(":", 1)[1])
            self.log.info("P2P listening on %s (%s wire)", self.p2p_server.address, self.p2p_wire)
            if getattr(self.args, "upnp", False):
                self._start_upnp(self.node.listen_port)
        self.connection_manager.start()
        return []

    def _start_upnp(self, listen_port: int) -> None:
        """Map the P2P listen port on the internet gateway and keep the
        lease alive (addressmanager configure_port_mapping + the
        port_mapping_extender service).  Discovery runs off-thread and the
        whole feature fails soft — no cooperative gateway, no mapping."""

        def run():
            import http.client as _http_client

            from kaspa_tpu.p2p.upnp import UpnpError, configure_port_mapping

            try:
                external_ip, extender = configure_port_mapping(listen_port)
            except (UpnpError, OSError, _http_client.HTTPException) as e:
                self.log.info("UPnP unavailable: %s", e)
                return
            stale = None
            with self._upnp_lock:
                if self._upnp_stopped:
                    # the daemon shut down while discovery was in flight:
                    # tear the fresh mapping down instead of leaking it
                    stale = extender
                else:
                    self.upnp_extender = extender
            if stale is not None:
                # outside the lock: stop() joins the renewal thread, and a
                # join under daemon.upnp would stall the shutdown path
                stale.stop()
                return
            if self.address_manager is not None:
                from kaspa_tpu.p2p.address_manager import NetAddress

                # gossiped to peers, excluded from our own outbound dials
                self.address_manager.add_local_address(NetAddress(external_ip, listen_port))
            self.log.info("publicly routable address %s:%d registered", external_ip, listen_port)

        self._upnp_lock = ranked_lock("daemon.upnp", reentrant=False)
        self._upnp_stopped = False
        threading.Thread(target=run, daemon=True, name="upnp-setup").start()

    def _stop_p2p_service(self) -> None:
        self.connection_manager.stop()
        if getattr(self, "_upnp_lock", None) is not None:
            with self._upnp_lock:
                self._upnp_stopped = True
                extender = getattr(self, "upnp_extender", None)
                self.upnp_extender = None
            if extender is not None:
                extender.stop()
        if self.p2p_server is not None:
            self.p2p_server.stop()
            self.p2p_server = None
        for peer in list(self.node.peers):
            if hasattr(peer, "close"):
                peer.close()

    def _start_fabric_service(self, _core) -> list:
        if self.fabric_mode == "serve":
            from kaspa_tpu.fabric.service import VerifyService

            self.fabric_service = VerifyService(self._fabric_arg or "127.0.0.1:18500")
            host, port = self.fabric_service.start()
            self.fabric_addr = f"{host}:{port}"
            self.log.info(
                "verify fabric serving on %s (%d slices)", self.fabric_addr, self.fabric_service.slices
            )
        else:
            from kaspa_tpu.fabric import balancer as fabric_balancer

            bal = fabric_balancer.configure(self._fabric_arg)
            live = sum(1 for s in bal.stats()["slices"] if s["alive"])
            self.log.info("verify fabric balancer over %s (%d live slices)", self._fabric_arg, live)
        return []

    def _stop_fabric_service(self) -> None:
        # only the serve side stops here (reverse bind order): the connect-
        # side balancer must outlive the pipeline drain in stop(), so its
        # tickets keep resolving until validation work is idle
        if self.fabric_service is not None:
            self.fabric_service.stop()
            self.fabric_service = None

    def _start_wrpc_service(self, _core) -> list:
        from kaspa_tpu.rpc.wrpc import WrpcServer

        host, port = self.args.rpclisten_wrpc.rsplit(":", 1)
        self.wrpc_server = WrpcServer(self, host, int(port))
        self.wrpc_server.start()
        return []

    def _stop_wrpc_service(self) -> None:
        if self.wrpc_server is not None:
            self.wrpc_server.stop()
            self.wrpc_server = None

    def _start_stratum_service(self, _core) -> list:
        from kaspa_tpu.bridge.stratum import StratumBridge, StratumServer
        from kaspa_tpu.consensus.processes.coinbase import MinerData
        from kaspa_tpu.crypto.addresses import Address, pay_to_address_script

        pay = getattr(self.args, "stratum_pay_address", None)
        if not pay:
            raise ValueError("--stratum requires --stratum-pay-address")
        spk = pay_to_address_script(Address.from_string(pay))
        miner_data = MinerData(spk, b"")

        from kaspa_tpu.consensus.api import ConsensusApi

        def template_source():
            with self._dispatch_lock:
                # same sync gate as the RPC path (rule_engine.rs should_mine):
                # stratum miners must not burn hashrate on a stale tip.
                # self.consensus re-resolves per call: staging swaps rebind it
                sink_ts = ConsensusApi(self.consensus).get_sink_timestamp()
                if not self.rule_engine.should_mine(sink_ts):
                    raise ValueError("node is not synced: block templates unavailable")
                # graftlint: allow(blocking-under-lock) -- template build runs consensus (and its device waves) under the dispatch lock by design, same gate as the RPC path
                return self.mining.get_block_template(miner_data)

        def submit(block):
            with self._dispatch_lock:
                # graftlint: allow(blocking-under-lock) -- stratum submit serializes with the RPC mutation path; insert+unorphan device waits are the locked section's job
                return self.node.submit_block(block)

        bridge = StratumBridge(template_source, submit)
        host, port = self.args.stratum.rsplit(":", 1)
        self.stratum_server = StratumServer(bridge, host, int(port))
        self.stratum_server.start()
        self.log.info("stratum bridge on %s", self.stratum_server.address)
        return []

    def _stop_stratum_service(self) -> None:
        if self.stratum_server is not None:
            self.stratum_server.stop()
            self.stratum_server = None

    def start(self) -> str:
        # device supervision up before any traffic: managed breaker, canary
        # prober, and (on warm non-CPU backends) the background pretrace of
        # manifest shapes — off the commit lock, the restart-warmth path
        from kaspa_tpu.resilience import supervisor

        supervisor.install()
        self._supervised = True
        self.overload.start(interval_s=0.5)
        self.core.start()
        seeds = getattr(self.args, "dnsseed", []) or []
        if seeds:
            # resolver latency must not block startup (a dead seed hangs
            # getaddrinfo for its full timeout, serially per seed)
            def _seed():
                n = self.address_manager.dns_seed(seeds, default_port=16111)
                self.log.info("dns seeding added %d addresses from %d seeds", n, len(seeds))

            threading.Thread(target=_seed, daemon=True, name="dnsseed").start()
        for peer_addr in getattr(self.args, "connect", []) or []:
            self.connect_peer(peer_addr)
        return self._rpc_addr

    def connect_peer(self, address: str):
        """Dial a peer over the wire and catch up from it (IBD).

        The dial retries with deterministic exponential backoff
        (KASPA_TPU_CONNECT_RETRIES attempts, default 5): a --connect seed
        peer that comes up moments after us — the normal case when a swarm
        starts N nodes in one burst, and common enough on real restarts —
        should not cost the only startup dial we'd otherwise make."""
        import time as _time

        from kaspa_tpu.p2p.address_manager import NetAddress
        from kaspa_tpu.p2p.transport import connect_outbound, get_codec

        attempts = max(1, int(os.environ.get("KASPA_TPU_CONNECT_RETRIES", "5")))
        peer = None
        for attempt in range(attempts):
            try:
                peer = connect_outbound(self.node, address, codec=get_codec(self.p2p_wire))
                break
            except (OSError, ConnectionError):
                if attempt == attempts - 1:
                    raise
                # deterministic (no jitter): 0.25s, 0.5s, 1s, 2s, capped 4s
                _time.sleep(min(0.25 * (2.0 ** attempt), 4.0))
        # register the RESOLVED address (getpeername) so the connection
        # manager's connected-set comparison matches and never re-dials
        na = getattr(peer, "peer_address", None)
        if na is not None:
            self.address_manager.add_address(na)
            self.address_manager.mark_connection_success(na)
        # connect-path IBD kick: ibd_from only sends the chain-info request
        # (no consensus access), so it needs no lock — the response flows
        # run under the reader thread's node-lock acquisition
        self.node.ibd_from(peer)
        return peer

    def stop(self) -> None:
        # overload ticker first: brownout actions must not re-engage while
        # the subsystems they reach into are being torn down below
        if getattr(self, "overload", None) is not None:
            self.overload.shutdown()
        self.core.shutdown()  # reverse bind order: p2p, rpc, tick (blocks
        # until services are down, even when another thread began the stop)
        # drain asynchronous validation work before the db handle goes away:
        # blocks in flight inside the pipeline and script jobs on the VM
        # fallback lane both write through consensus stores — killing them
        # mid-commit is exactly the torn state the journal exists to absorb,
        # so an ORDERLY stop should not manufacture one
        try:
            self.node.pipeline.wait_for_idle(timeout=30.0)
        except Exception:  # noqa: BLE001 - drain is best-effort on the way down
            pass
        self.node._drop_ibd_pipeline()
        self.node.pipeline.shutdown()
        from kaspa_tpu.ops import dispatch as verify_dispatch
        from kaspa_tpu.txscript import batch as script_batch

        script_batch.drain_fallback_pool(timeout=10.0)
        if self.fabric_mode == "connect":
            # the balancer drains (remote + degraded lanes) before the
            # generic dispatch shutdown below closes whatever engine remains
            from kaspa_tpu.fabric import balancer as fabric_balancer

            fabric_balancer.shutdown(timeout=10.0)
        # same barrier for the async coalescing queue: flush staged verify
        # chunks and block until every callback has resolved — tickets
        # resolving after the db handle closes would write sig-cache entries
        # for a consensus object that is already torn down.  shutdown()
        # (vs drain) bounds the wait: if the dispatcher thread is wedged
        # inside a hung device call, remaining tickets fail with
        # DispatchAbandoned instead of blocking process exit
        verify_dispatch.shutdown(timeout=10.0)
        from kaspa_tpu.resilience import supervisor

        with self._dispatch_lock:
            # stop() may race itself; release the supervision ref once
            was_supervised, self._supervised = getattr(self, "_supervised", False), False
        if was_supervised:
            supervisor.shutdown()
        # serving tier down before the stores: the broadcaster detaches from
        # the notifier (no new fanout), then the index unhooks its listener
        # and closes its own db.  Snapshot-and-null under the lock, close
        # outside it: broadcaster.close() joins the fanout thread, and a
        # racing stop() sees None instead of double-closing
        with self._dispatch_lock:
            bc = getattr(self, "broadcaster", None)
            self.broadcaster = None
            pool, self.serving_pool = getattr(self, "serving_pool", None), None
            ui, self.utxoindex = self.utxoindex, None
        if bc is not None:
            bc.close()
        if pool is not None:
            pool.close()
        if ui is not None:
            ui.close()
        # quiesce dispatch before closing the native handle: an in-flight
        # handler finishes under the lock; later ones see db == None and
        # stage() no-ops (server is already down, nothing new arrives).
        # db re-checked under the lock: stop() may race itself (shutdown
        # RPC thread vs main's wait_for_shutdown path).
        with self._dispatch_lock:
            if self.db is not None:
                # orderly shutdown: snapshot reachability for the fast
                # restart path (crashes skip this and rebuild instead);
                # its flush also commits any other pending ops
                self.consensus.save_reachability_snapshot()
                self.consensus.storage.db = None
                self.db.close()
                self.db = None


class NotificationClient:
    """Persistent RPC connection with notification streaming (the
    rpc/grpc/client + notify subscriber pair).  ``call`` issues regular
    requests on the same socket; streamed ``{"notification": ...}`` lines
    land in ``self.notifications`` (a Queue) as (event, data) tuples."""

    def __init__(self, addr: str, timeout: float = 30.0):
        import queue as _queue

        host, port = addr.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)), timeout=timeout)
        self._rfile = self._sock.makefile("rb")
        self._timeout = timeout
        # graftlint: allow(unbounded-queue) -- client-side helper; one request in flight, reader thread drains
        self._responses: _queue.Queue = _queue.Queue()
        self.notifications: _queue.Queue = _queue.Queue()  # graftlint: allow(unbounded-queue) -- client-side helper for tests/CLI; consumer polls per scripted step
        self._next_id = 0
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop, daemon=True, name="rpc-notify-reader")
        self._reader.start()

    def _read_loop(self):
        try:
            for line in self._rfile:
                msg = json.loads(line)
                if "notification" in msg:
                    n = msg["notification"]
                    self.notifications.put((n["event"], n["data"]))
                else:
                    self._responses.put(msg)
        except (OSError, ValueError):
            pass
        self._responses.put(None)  # connection closed

    def call(self, method: str, params: dict | None = None):
        import queue as _queue
        import time as _time

        self._next_id += 1
        req_id = self._next_id
        self._sock.sendall(
            (json.dumps({"id": req_id, "method": method, "params": params or {}}) + "\n").encode()
        )
        deadline = _time.monotonic() + self._timeout
        while True:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"rpc call {method} timed out after {self._timeout}s")
            try:
                resp = self._responses.get(timeout=remaining)
            except _queue.Empty:
                raise TimeoutError(f"rpc call {method} timed out after {self._timeout}s") from None
            if resp is None:
                raise ConnectionError("connection closed")
            if resp.get("id") != req_id:
                continue  # stale response from an earlier timed-out call
            if "error" in resp:
                raise RuntimeError(resp["error"])
            return resp["result"]

    def subscribe(self, event: str, addresses: list[str] | None = None):
        params = {"event": event}
        if addresses:
            params["addresses"] = addresses
        return self.call("subscribe", params)

    def unsubscribe(self, event: str, addresses: list[str] | None = None):
        params = {"event": event}
        if addresses:
            params["addresses"] = addresses
        return self.call("unsubscribe", params)

    def next_notification(self, timeout: float = 30.0):
        return self.notifications.get(timeout=timeout)

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


def rpc_call(addr: str, method: str, params: dict | None = None, timeout: float = 30.0):
    """Minimal line-JSON-RPC client (rpc/grpc/client equivalent)."""
    host, port = addr.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=timeout) as s:
        s.sendall((json.dumps({"id": 1, "method": method, "params": params or {}}) + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(65536)
            if not chunk:
                raise ConnectionError(f"connection closed mid-response ({len(buf)} bytes buffered)")
            buf += chunk
    resp = json.loads(buf)
    if "error" in resp:
        raise RuntimeError(resp["error"])
    return resp["result"]


def main(argv=None) -> None:
    from kaspa_tpu.core.log import init_logger

    args = parse_args(argv)
    os.makedirs(args.appdir, exist_ok=True)
    init_logger(log_file=os.path.join(args.appdir, "kaspad.log"))
    daemon = Daemon(args)
    daemon.core.install_signal_handlers()  # SIGINT/SIGTERM -> ordered stop
    addr = daemon.start()
    print(f"kaspa-tpu node listening on {addr} (network {daemon.params.name})")
    try:
        daemon.core.wait_for_shutdown()
        daemon.stop()
    except BaseException:
        # crash path: the flight ring is the black box — flush it beside the
        # log before the interpreter unwinds (no-op when --flight is off)
        if getattr(args, "flight", False):
            from kaspa_tpu.observability import flight

            try:
                flight.dump(reason="crash")
            except Exception:  # noqa: BLE001 - never mask the original crash
                pass
        raise


if __name__ == "__main__":
    main()
