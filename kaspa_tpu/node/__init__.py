from kaspa_tpu.node.daemon import Daemon, DaemonArgs  # noqa: F401
