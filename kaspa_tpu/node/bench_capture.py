"""Recurring-timer hardware bench capture (ROADMAP item 1).

`bench.py` already probes the device at session start and falls back to a
timestamped wedge dossier when the chip is wedged.  This service closes
the loop for a *long-running daemon*: riding the 10s tick, it re-probes
the device every KASPA_TPU_BENCH_RECHECK_S seconds (default 900), and the
moment a trivial jit answers it runs the full bench in a fresh
subprocess, recording the captured number — best + bounded history — in
``<appdir>/BENCH_CAPTURE.json``.  A wedged chip therefore costs one
cheap probe per interval, while an unwedged chip is measured within one
interval of coming back.

Everything runs on a daemonized worker thread guarded by a non-blocking
busy flag: the tick callback itself never blocks the metrics cadence,
and overlapping captures are impossible.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

_HISTORY_CAP = 50


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _last_json_line(out: str) -> dict | None:
    for line in reversed(out.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


class BenchCapture:
    def __init__(self, appdir: str, logger=None, bench_path: str | None = None):
        self.interval_s = float(os.environ.get("KASPA_TPU_BENCH_RECHECK_S", "900"))
        self.probe_timeout_s = float(os.environ.get("KASPA_TPU_BENCH_PROBE_TIMEOUT_S", "180"))
        self.bench_timeout_s = float(os.environ.get("KASPA_TPU_BENCH_CAPTURE_TIMEOUT_S", "1800"))
        self.bench_path = bench_path or os.environ.get(
            "KASPA_TPU_BENCH_PATH", os.path.join(_repo_root(), "bench.py")
        )
        self.out_path = os.path.join(appdir, "BENCH_CAPTURE.json")
        self.log = logger
        self._busy = threading.Lock()  # graftlint: allow(raw-lock) -- single-writer busy latch for the bench artifact; never nests
        self._last_attempt = float("-inf")  # first tick probes immediately
        self.captures = 0
        self.probe_failures = 0

    # -- tick entry point ----------------------------------------------------

    def tick(self) -> None:
        """10s-tick callback: rate-limited, never blocks the tick thread."""
        now = time.monotonic()
        if now - self._last_attempt < self.interval_s:
            return
        if not self._busy.acquire(blocking=False):
            return  # a capture is still running from a previous interval
        self._last_attempt = now
        threading.Thread(target=self._capture_once, daemon=True, name="bench-capture").start()

    # -- worker --------------------------------------------------------------

    def _run_child(self, argv: list[str], timeout_s: float) -> dict | None:
        env = dict(os.environ)
        env["PYTHONPATH"] = _repo_root() + os.pathsep + env.get("PYTHONPATH", "")
        # bypass bench.py's cached-wedge fast-fail: this service exists to
        # notice device *recovery*, so every probe must be a fresh one
        env["KASPA_TPU_BENCH_FORCE_PROBE"] = "1"
        try:
            proc = subprocess.run(
                [sys.executable, self.bench_path, *argv],
                cwd=_repo_root(), env=env, timeout=timeout_s,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
        except (subprocess.TimeoutExpired, OSError):
            return None
        return _last_json_line(proc.stdout or "")

    def _capture_once(self) -> None:
        try:
            probe = self._run_child(["--probe"], self.probe_timeout_s)
            if not probe or not probe.get("probe_ok"):
                self.probe_failures += 1
                if self.log:
                    self.log.info(
                        "bench capture: device probe negative (%s); next attempt in %.0fs",
                        (probe or {}).get("error", "no probe output"), self.interval_s,
                    )
                return
            # a trivial jit answered: capture the real number now
            result = self._run_child([], self.bench_timeout_s)
            if not result or "value" not in result:
                if self.log:
                    self.log.warning("bench capture: probe ok but bench run produced no result")
                return
            self.captures += 1
            self._record(result)
        except Exception:  # noqa: BLE001 - a capture bug must not kill the tick
            if self.log:
                self.log.exception("bench capture failed")
        finally:
            self._busy.release()

    def _record(self, result: dict) -> None:
        doc = {"best": None, "history": []}
        try:
            with open(self.out_path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            pass
        entry = {
            "captured": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "value": result.get("value"),
            "metric": result.get("metric"),
            "platform": result.get("platform"),
            "batch": result.get("batch"),
        }
        doc.setdefault("history", []).append(entry)
        doc["history"] = doc["history"][-_HISTORY_CAP:]
        best = doc.get("best")
        if not best or (entry["value"] or 0) > (best.get("value") or 0):
            doc["best"] = entry
        doc["updated"] = entry["captured"]
        tmp = self.out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        os.replace(tmp, self.out_path)
        if self.log:
            self.log.info(
                "bench capture: %.1f %s recorded (best %.1f) -> %s",
                entry["value"] or 0.0, entry["metric"] or "", (doc["best"]["value"] or 0.0), self.out_path,
            )
