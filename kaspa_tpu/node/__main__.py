from kaspa_tpu.utils import jax_setup

jax_setup.setup()

from kaspa_tpu.node.daemon import main

main()
