from kaspa_tpu.node.daemon import main

main()
