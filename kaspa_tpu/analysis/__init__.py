"""graftlint: project-invariant static analysis for the kaspa-tpu runtime.

An AST-based checker framework encoding the invariants this repo keeps
re-learning at runtime (see ISSUE 13 / README "Static analysis"):

    blocking-under-lock   no device dispatch / Future.result / sleep /
                          socket recv inside a ``with <lock>`` body
                          (one-hop call-graph expansion included)
    raw-lock              threading.Lock()/RLock() construction outside
                          utils/sync.py must be a ranked LockCtx
    tracer-hazard         module-level caches, host coercions and
                          unrolled loops inside jitted code
    trace-ctx-handoff     queue handoffs in instrumented subsystems must
                          carry the flight-recorder trace context
    registry-hygiene      fault points match the resilience/faults.py
                          catalog; metric names are convention-clean and
                          registered once
    unbounded-queue       every deque()/Queue() outside utils/ states its
                          overflow policy (maxlen/maxsize, a producer-side
                          capacity check, or a justified pragma)

Suppression: ``# graftlint: allow(<checker-id>) -- <justification>`` on
the offending line (or alone on the line above).  A pragma without a
justification is itself an error — every silence is documented.

Run: ``python -m kaspa_tpu.analysis`` (or ``tools/lint.py``).
"""

from kaspa_tpu.analysis.core import (  # noqa: F401
    CHECKERS,
    Finding,
    Project,
    register_checker,
    run_project,
)
