"""graftlint v2: whole-program static analysis for the kaspa-tpu runtime.

An AST-based checker framework encoding the invariants this repo keeps
re-learning at runtime (see ISSUEs 13/15 / README "Static analysis").
The v2 engine builds a module-qualified project call graph
(``analysis/callgraph.py``) and runs fixpoint propagation of may-block /
may-raise facts over it, so interprocedural checkers see chains of any
depth — not one hop.

Per-file checkers:

    blocking-under-lock   no device dispatch / Future.result / sleep /
                          socket recv inside a ``with <lock>`` body —
                          including *transitively*, through call chains
                          of any depth (fixpoint over the call graph)
    exception-path        manual lock.acquire() followed by a
                          raise-reachable call before .release() without
                          try/finally leaks the lock on the throw path
    resource-lifecycle    Ticket/AdmissionTicket resolve exactly once on
                          every path; flight spans close;
                          faults.suppress() is a context manager
    raw-lock              threading.Lock()/RLock() construction outside
                          utils/sync.py must be a ranked LockCtx
    tracer-hazard         module-level caches, host coercions and
                          unrolled loops inside jitted code
    trace-ctx-handoff     queue handoffs in instrumented subsystems must
                          carry the flight-recorder trace context
    registry-hygiene      fault points match the resilience/faults.py
                          catalog; metric names are convention-clean and
                          registered once
    unbounded-queue       every deque()/Queue() outside utils/ states its
                          overflow policy (maxlen/maxsize, a producer-side
                          capacity check, or a justified pragma)

Project checkers (run once over the whole tree):

    env-knob              every KASPA_TPU_* read reconciles against the
                          committed KNOBS.md catalog (regen: --knobs)
    kernel-shape          [gated: --shapes] jax.eval_shape every reachable
                          kernel family x bucket x mesh signature; fail on
                          dtype drift and WARM_COVERAGE holes

Suppression: ``# graftlint: allow(<checker-id>) -- <justification>`` on
the offending line, alone on the line above, or anywhere on a multi-line
statement's span.  A pragma without a justification is itself an error —
every silence is documented.  ``--ratchet`` pins the suppression count
and per-checker finding counts to the committed LINT.json baseline.

Run: ``python -m kaspa_tpu.analysis`` (or ``tools/lint.py``).
"""

from kaspa_tpu.analysis.core import (  # noqa: F401
    CHECKERS,
    PROJECT_CHECKERS,
    Finding,
    Project,
    register_checker,
    register_project_checker,
    run_project,
)
