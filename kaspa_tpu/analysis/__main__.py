"""graftlint CLI: ``python -m kaspa_tpu.analysis [paths...]``.

Exit status 0 iff no active findings (suppressed-with-justification
pragmas don't count) and — under ``--ratchet`` — no regression against
the committed baseline.  ``--json PATH`` additionally writes the full
LINT.json document; the human table always goes to stdout.

v2 flags:
  --shapes    enable the gated kernel-shape audit (imports jax)
  --knobs     (re)generate KNOBS.md from the env-knob census and exit
  --ratchet   compare against the committed LINT.json baseline: fail if
              the suppression count or any per-checker active-finding
              count grew (reads the baseline BEFORE overwriting --json)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kaspa_tpu.analysis import CHECKERS, run_project
from kaspa_tpu.analysis.core import PROJECT_CHECKERS
import kaspa_tpu.analysis.checkers  # noqa: F401  (registers the per-file checkers)
import kaspa_tpu.analysis.lifecycle  # noqa: F401  (resource-lifecycle, exception-path)
import kaspa_tpu.analysis.envknobs  # noqa: F401  (env-knob)
import kaspa_tpu.analysis.shapes  # noqa: F401  (kernel-shape, gated)


def _default_paths(root: str) -> list[str]:
    return [os.path.join(root, "kaspa_tpu")]


def _load_baseline(path: str) -> dict | None:
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def check_ratchet(baseline: dict | None, report: dict) -> list[str]:
    """Regressions of ``report`` against the committed ``baseline``:
    suppression count and per-checker active counts may shrink, never
    grow.  A missing/unreadable baseline is itself a failure — the
    ratchet only means something against a committed document."""
    if baseline is None:
        return ["ratchet: no committed baseline LINT.json to compare against"]
    out: list[str] = []
    base_supp = len(baseline.get("suppressed", []))
    new_supp = len(report.get("suppressed", []))
    if new_supp > base_supp:
        out.append(
            f"ratchet: suppression count grew {base_supp} -> {new_supp} "
            "(new pragmas need the debt paid down elsewhere)"
        )
    base_counts = baseline.get("counts", {})
    for cid, n in sorted(report.get("counts", {}).items()):
        if n > base_counts.get(cid, 0):
            out.append(
                f"ratchet: {cid} active findings grew "
                f"{base_counts.get(cid, 0)} -> {n}"
            )
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kaspa_tpu.analysis",
        description="graftlint: project-invariant static analysis (v2 whole-program engine)",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to lint (default: the kaspa_tpu package)")
    ap.add_argument("--root", default=None, help="repo root for relative paths (default: cwd)")
    ap.add_argument("--json", dest="json_path", default=None, help="write LINT.json here")
    ap.add_argument("--list-checkers", action="store_true", help="print the checker catalog and exit")
    ap.add_argument("--shapes", action="store_true", help="enable the gated kernel-shape audit (imports jax)")
    ap.add_argument("--knobs", action="store_true", help="(re)generate KNOBS.md from the env-knob census and exit")
    ap.add_argument(
        "--ratchet",
        action="store_true",
        help="fail if suppressions or per-checker findings grew vs the committed --json baseline",
    )
    ap.add_argument("-q", "--quiet", action="store_true", help="suppress the summary table")
    args = ap.parse_args(argv)

    if args.list_checkers:
        for cid in sorted(CHECKERS):
            print(f"{cid:22s} {CHECKERS[cid].description}")
        for cid in sorted(PROJECT_CHECKERS):
            spec = PROJECT_CHECKERS[cid]
            gate = " [gated]" if spec.gated else ""
            print(f"{cid:22s} {spec.description}{gate}")
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    paths = [os.path.abspath(p) for p in args.paths] or _default_paths(root)

    if args.knobs:
        from kaspa_tpu.analysis.core import Project, collect_files
        from kaspa_tpu.analysis.envknobs import render_knobs_md, scan_knob_sites

        project = Project(root, collect_files(paths, root))
        knobs_path = os.path.join(root, "KNOBS.md")
        existing = None
        if os.path.isfile(knobs_path):
            with open(knobs_path, encoding="utf-8") as fh:
                existing = fh.read()
        census = scan_knob_sites(project)
        with open(knobs_path, "w", encoding="utf-8") as fh:
            fh.write(render_knobs_md(census, existing))
        print(f"KNOBS.md: {len(census)} knobs from {sum(len(v) for v in census.values())} sites")
        return 0

    baseline = _load_baseline(args.json_path) if (args.ratchet and args.json_path) else None
    options = {"kernel-shape": True} if args.shapes else None
    report = run_project(paths, root=root, options=options)

    ratchet_failures: list[str] = []
    if args.ratchet:
        ratchet_failures = check_ratchet(baseline, report)
        report["ratchet"] = {"ok": not ratchet_failures, "failures": ratchet_failures}

    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")

    ok = report["ok"] and not ratchet_failures
    if not args.quiet:
        for finding in report["findings"]:
            print(f"{finding['path']}:{finding['line']}: [{finding['checker']}] {finding['message']}")
        for msg in ratchet_failures:
            print(msg)
        n_active = len(report["findings"])
        n_supp = len(report["suppressed"])
        state = "clean" if ok else "FAILED"
        print(
            f"graftlint: {state} — {report['files']} files, "
            f"{n_active} finding(s), {n_supp} suppressed "
            f"({len(report['checkers'])} checkers, engine {report['engine']})"
        )
        if report["counts"]:
            for cid, n in sorted(report["counts"].items()):
                print(f"  {cid:22s} {n}")
    elif ratchet_failures:
        for msg in ratchet_failures:
            print(msg, file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
