"""graftlint CLI: ``python -m kaspa_tpu.analysis [paths...]``.

Exit status 0 iff no active findings (suppressed-with-justification
pragmas don't count).  ``--json PATH`` additionally writes the full
LINT.json document; the human table always goes to stdout.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from kaspa_tpu.analysis import CHECKERS, run_project
import kaspa_tpu.analysis.checkers  # noqa: F401  (registers the checkers)


def _default_paths(root: str) -> list[str]:
    return [os.path.join(root, "kaspa_tpu")]


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kaspa_tpu.analysis",
        description="graftlint: project-invariant static analysis",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to lint (default: the kaspa_tpu package)")
    ap.add_argument("--root", default=None, help="repo root for relative paths (default: cwd)")
    ap.add_argument("--json", dest="json_path", default=None, help="write LINT.json here")
    ap.add_argument("--list-checkers", action="store_true", help="print the checker catalog and exit")
    ap.add_argument("-q", "--quiet", action="store_true", help="suppress the summary table")
    args = ap.parse_args(argv)

    if args.list_checkers:
        for cid in sorted(CHECKERS):
            print(f"{cid:22s} {CHECKERS[cid].description}")
        return 0

    root = os.path.abspath(args.root or os.getcwd())
    paths = [os.path.abspath(p) for p in args.paths] or _default_paths(root)
    report = run_project(paths, root=root)

    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")

    if not args.quiet:
        for finding in report["findings"]:
            print(f"{finding['path']}:{finding['line']}: [{finding['checker']}] {finding['message']}")
        n_active = len(report["findings"])
        n_supp = len(report["suppressed"])
        state = "clean" if report["ok"] else "FAILED"
        print(
            f"graftlint: {state} — {report['files']} files, "
            f"{n_active} finding(s), {n_supp} suppressed "
            f"({len(report['checkers'])} checkers)"
        )
        if report["counts"]:
            for cid, n in sorted(report["counts"].items()):
                print(f"  {cid:22s} {n}")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
