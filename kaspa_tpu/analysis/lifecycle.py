"""Resource-lifecycle protocol checking (graftlint v2).

Every subsystem promises ``lost == 0``: a Ticket / AdmissionTicket handed
out MUST resolve exactly once on every path; a flight span MUST close; a
``faults.suppress()`` is a context manager, not a statement.  Those
contracts were enforced only dynamically (the sustain drills count lost
tickets after the fact) — this module enforces them at lint time with a
branch-sensitive walk over each function body.

Protocol registry (``PROTOCOLS``) — each entry names how a tracked value
is *acquired*, which method calls *resolve* it, and what counts as an
*escape* (ownership transfer: returned, passed to a call, stored into an
attribute/container — after which resolution is someone else's job):

- ``ticket``:  ``x = <recv>.submit(...)`` / ``x = <recv>.admit(...)`` /
  ``x = Ticket(...)`` / ``x = AdmissionTicket(...)``.  Resolved by
  ``.wait()`` / ``.resolve()`` / ``._resolve()`` / ``.cancel()``.  A path
  that returns or falls off the function with the value still pending
  drops the ticket — exactly the early-return bug class the overload
  plane had to hand-patch.  Resolving twice on one path is also a
  finding (``lost == 0`` is an exactly-once contract, not at-least-once).
- ``span``:    ``trace.span(...)`` must be entered — a with-item, or
  escaped to a caller; a bare/assigned-and-never-entered span silently
  detaches its subtree from the block trace.
- ``suppress``: ``faults.suppress()`` returns a context manager; calling
  it as a statement arms nothing and the next injected fault fires
  through the "suppressed" section.

Exception paths: raise-exits do NOT require resolution (the exception
propagates — the caller never received the value), matching how
``submit()`` surfaces shutdown.  The separate ``exception-path`` checker
instead flags manual ``lock.acquire()`` followed by raise-reachable calls
(per the call graph's fixpoint may-raise fact) without ``try/finally``.
"""

from __future__ import annotations

import ast

from kaspa_tpu.analysis.blocking import _terminal_name, is_lock_expr
from kaspa_tpu.analysis.core import Finding, Project, SourceFile, register_checker

# -- protocol registry -------------------------------------------------------

ACQUIRE_METHODS = {"submit", "admit"}  # x = recv.submit(...) hands out a ticket
ACQUIRE_CTORS = {"Ticket", "AdmissionTicket"}
# .submit()/.admit() only hands out a ticket on dispatcher-like receivers
# (bridge.submit() returns a bool; pool.submit() fire-and-forget is fine)
_RECV_HINTS = ("ingest", "dispatch", "engine", "pool", "tier", "executor", "coalesc")
# producer side resolves exactly once; calling twice on one path is a bug
PRODUCER_RESOLVE = {"resolve", "_resolve", "cancel"}
# consumer side: waiting/consuming the outcome discharges the obligation
# and may legitimately repeat (wait() then raise_for_status())
CONSUMER_RESOLVE = {"wait", "raise_for_status"}
RESOLVE_METHODS = PRODUCER_RESOLVE | CONSUMER_RESOLVE
# reading the outcome fields consumes an (already-resolved) ticket too —
# ingest.admit() returns resolved tickets whose callers branch on .status
CONSUME_ATTRS = {"status", "error", "evicted"}
# pure queries that must NOT count as resolution (reading liveness keeps
# the obligation alive — `if t.done()` is exactly the early-return shape)
QUERY_METHODS = {"done", "stats", "render"}

PROTOCOLS = {
    "ticket": {
        "description": "Ticket/AdmissionTicket must resolve exactly once on every path",
        "acquire_methods": ACQUIRE_METHODS,
        "acquire_ctors": ACQUIRE_CTORS,
        "resolve": RESOLVE_METHODS,
    },
    "span": {"description": "flight spans must close (use `with trace.span(...)`)"},
    "suppress": {"description": "faults.suppress() must be a context manager"},
}

_PENDING, _RESOLVED, _ESCAPED = "pending", "resolved", "escaped"
_MAX_STATES = 32  # path-merge cap: beyond this, pessimistically union


class _PathReport:
    def __init__(self):
        self.findings: list[tuple] = []  # (line, message) dedup'd
        self._seen: set[tuple] = set()

    def add(self, line: int, message: str) -> None:
        key = (line, message)
        if key not in self._seen:
            self._seen.add(key)
            self.findings.append(key)


def _is_acquire_call(value: ast.AST) -> int | None:
    """Acquire line if this expression hands out a tracked ticket value."""
    if not isinstance(value, ast.Call):
        return None
    name = _terminal_name(value.func)
    if isinstance(value.func, ast.Attribute) and name in ACQUIRE_METHODS:
        recv = _terminal_name(value.func.value).lower()
        if any(h in recv for h in _RECV_HINTS):
            return value.lineno
        return None
    if isinstance(value.func, ast.Name) and name in ACQUIRE_CTORS:
        return value.lineno
    return None


def _mentions(expr: ast.AST | None, names) -> set[str]:
    if expr is None:
        return set()
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name) and n.id in names}


def _process_expr(expr: ast.AST | None, state: dict, report: _PathReport) -> None:
    """Update ticket states for one expression: resolve-method calls mark
    resolved (twice = finding), passing the value anywhere marks escaped."""
    if expr is None:
        return
    for n in ast.walk(expr):
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name):
            v = n.value.id
            if v in state and n.attr in CONSUME_ATTRS and state[v][0] == _PENDING:
                state[v] = (_ESCAPED, n.lineno)  # outcome consumed by field read
        if not isinstance(n, ast.Call):
            continue
        if isinstance(n.func, ast.Attribute) and isinstance(n.func.value, ast.Name):
            v = n.func.value.id
            if v in state:
                if n.func.attr in PRODUCER_RESOLVE:
                    if state[v][0] == _RESOLVED:
                        report.add(
                            n.lineno,
                            f"`{v}` resolved twice on one path (first at line "
                            f"{state[v][1]}): tickets resolve exactly once",
                        )
                    state[v] = (_RESOLVED, n.lineno)
                elif n.func.attr in CONSUMER_RESOLVE and state[v][0] == _PENDING:
                    state[v] = (_ESCAPED, n.lineno)
                # queries and other attribute access keep the obligation
        for a in list(n.args) + [k.value for k in n.keywords]:
            for v in _mentions(a, state):
                if state[v][0] == _PENDING:
                    state[v] = (_ESCAPED, n.lineno)


def _check_exit(state: dict, line: int, report: _PathReport, why: str) -> None:
    for v, (status, acq_line) in state.items():
        if status == _PENDING:
            report.add(
                acq_line,
                f"ticket `{v}` acquired here may go unresolved: {why} at line "
                f"{line} drops it (resolve, return, or hand it off on every path)",
            )


def _merge(states: list[dict]) -> list[dict]:
    uniq: list[dict] = []
    for st in states:
        if st not in uniq:
            uniq.append(st)
    if len(uniq) <= _MAX_STATES:
        return uniq
    # pessimistic union: a var is pending if pending in ANY state
    merged: dict = {}
    for st in uniq:
        for v, val in st.items():
            if v not in merged or val[0] == _PENDING:
                merged[v] = val
    return [merged]


def _exec_block(stmts: list, states: list[dict], report: _PathReport) -> list[tuple]:
    """Abstractly execute a statement list; returns [(exit_kind, state)]
    with exit_kind in {"fall", "return", "raise", "break", "continue"}."""
    exits: list[tuple] = []
    for stmt in stmts:
        new_states: list[dict] = []
        for st in states:
            for kind, st2 in _exec_stmt(stmt, st, report):
                if kind == "fall":
                    new_states.append(st2)
                else:
                    exits.append((kind, st2))
        states = _merge(new_states)
        if not states:
            break
    exits.extend(("fall", st) for st in states)
    return exits


def _exec_stmt(stmt: ast.AST, state: dict, report: _PathReport) -> list[tuple]:
    state = dict(state)
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return [("fall", state)]  # nested defs run later, elsewhere
    if isinstance(stmt, ast.Return):
        _process_expr(stmt.value, state, report)
        for v in _mentions(stmt.value, state):
            if state[v][0] == _PENDING:
                state[v] = (_ESCAPED, stmt.lineno)
        _check_exit(state, stmt.lineno, report, "return")
        return [("return", state)]
    if isinstance(stmt, ast.Raise):
        # the exception propagates: the caller never received the value,
        # so a pending ticket on a raise path is NOT a drop
        return [("raise", state)]
    if isinstance(stmt, (ast.Break, ast.Continue)):
        return [("break" if isinstance(stmt, ast.Break) else "continue", state)]
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        value = stmt.value
        targets = stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        acq = _is_acquire_call(value) if isinstance(stmt, ast.Assign) else None
        if acq is not None and len(targets) == 1 and isinstance(targets[0], ast.Name):
            v = targets[0].id
            if v in state and state[v][0] == _PENDING:
                report.add(
                    state[v][1],
                    f"ticket `{v}` acquired here is overwritten at line "
                    f"{stmt.lineno} while still unresolved",
                )
            state[v] = (_PENDING, acq)
            return [("fall", state)]
        _process_expr(value, state, report)
        # storing a tracked value into an attribute/subscript/container
        # transfers ownership
        if any(not isinstance(t, ast.Name) for t in targets):
            for v in _mentions(value, state):
                if state[v][0] == _PENDING:
                    state[v] = (_ESCAPED, stmt.lineno)
        else:
            for t in targets:
                if isinstance(t, ast.Name) and t.id in state and state[t.id][0] == _PENDING:
                    # plain reassignment drops the pending value
                    if not _mentions(value, {t.id}):
                        report.add(
                            state[t.id][1],
                            f"ticket `{t.id}` acquired here is overwritten at "
                            f"line {stmt.lineno} while still unresolved",
                        )
                        del state[t.id]
        return [("fall", state)]
    if isinstance(stmt, ast.Expr):
        _process_expr(stmt.value, state, report)
        return [("fall", state)]
    if isinstance(stmt, ast.If):
        _process_expr(stmt.test, state, report)
        return _exec_block(stmt.body, [dict(state)], report) + _exec_block(
            stmt.orelse, [dict(state)], report
        )
    if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
        if isinstance(stmt, ast.While):
            _process_expr(stmt.test, state, report)
        else:
            _process_expr(stmt.iter, state, report)
        body_exits = _exec_block(stmt.body, [dict(state)], report)
        after: list[dict] = [dict(state)]  # zero iterations
        out: list[tuple] = []
        for kind, st in body_exits:
            if kind in ("fall", "break", "continue"):
                after.append(st)
            else:
                out.append((kind, st))
        out.extend(_exec_block(stmt.orelse, _merge(after), report))
        return out
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            _process_expr(item.context_expr, state, report)
        return _exec_block(stmt.body, [dict(state)], report)
    if isinstance(stmt, ast.Try):
        body_exits = _exec_block(stmt.body, [dict(state)], report)
        out: list[tuple] = []
        fall_states: list[dict] = []
        for kind, st in body_exits:
            if kind == "fall":
                fall_states.append(st)
            elif kind == "raise" and stmt.handlers:
                pass  # swallowed: handler paths below model it
            else:
                out.append((kind, st))
        for h in stmt.handlers:
            out.extend(_exec_block(h.body, [dict(state)], report))
        out.extend(_exec_block(stmt.orelse, _merge(fall_states), report))
        if stmt.finalbody:
            final_out: list[tuple] = []
            for kind, st in out:
                for fkind, fst in _exec_block(stmt.finalbody, [st], report):
                    final_out.append((fkind if fkind != "fall" else kind, fst))
            out = final_out
        return out
    # anything else (pass, assert, del, global, import...) — process
    # embedded expressions conservatively and fall through
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, ast.expr):
            _process_expr(child, state, report)
    return [("fall", state)]


def _has_acquire(fn_node: ast.AST) -> bool:
    return any(
        isinstance(n, ast.Call) and _is_acquire_call(n) is not None
        for n in ast.walk(fn_node)
    )


@register_checker(
    "resource-lifecycle",
    "protocol values (Ticket/AdmissionTicket resolve exactly once per "
    "path; flight spans close; faults.suppress() is a context manager) "
    "tracked through branches and returns",
)
def check_resource_lifecycle(project: Project, f: SourceFile) -> list[Finding]:
    out: list[Finding] = []
    # -- ticket protocol: branch-sensitive per-function walk ---------------
    for node in ast.walk(f.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not _has_acquire(node):
            continue
        report = _PathReport()
        exits = _exec_block(node.body, [{}], report)
        end_line = node.body[-1].end_lineno or node.body[-1].lineno
        for kind, st in exits:
            if kind == "fall":
                _check_exit(st, end_line, report, "falling off the function")
        for line, message in sorted(report.findings):
            out.append(Finding(f.rel, line, "resource-lifecycle", message))
    # -- span + suppress protocols: structural, whole-file -----------------
    out.extend(_check_span_and_suppress(f))
    return out


def _check_span_and_suppress(f: SourceFile) -> list[Finding]:
    out: list[Finding] = []
    with_items: set[int] = set()  # id() of context_expr nodes
    assigned_spans: dict[str, int] = {}
    entered_names: set[str] = set()
    for node in ast.walk(f.tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                with_items.add(id(item.context_expr))
                name = _terminal_name(item.context_expr)
                if isinstance(item.context_expr, ast.Name):
                    entered_names.add(item.context_expr.id)
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        if name == "span" and _span_receiver_ok(node):
            if id(node) in with_items:
                continue
            parent_assign = _assigned_name(f.tree, node)
            if parent_assign is not None and parent_assign in entered_names:
                continue  # `sp = trace.span(...)` later entered via `with sp:`
            if _escapes(f.tree, node):
                continue  # returned / passed on: the receiver must close it
            out.append(
                Finding(
                    f.rel, node.lineno, "resource-lifecycle",
                    "flight span is never entered/closed: use `with "
                    "trace.span(...)` so the subtree stays attached to the "
                    "block trace",
                )
            )
        elif name == "suppress" and _suppress_receiver_ok(node):
            if id(node) not in with_items:
                out.append(
                    Finding(
                        f.rel, node.lineno, "resource-lifecycle",
                        "faults.suppress() returns a context manager — calling "
                        "it as a statement arms nothing (write `with "
                        "faults.suppress():`)",
                    )
                )
    return out


def _span_receiver_ok(node: ast.Call) -> bool:
    if isinstance(node.func, ast.Attribute):
        return _terminal_name(node.func.value) == "trace"
    return False  # bare span(...) is too generic a name to police


def _suppress_receiver_ok(node: ast.Call) -> bool:
    if isinstance(node.func, ast.Attribute):
        recv = _terminal_name(node.func.value).lower()
        return "fault" in recv  # faults / faults_mod / FAULTS
    return False


def _assigned_name(tree: ast.AST, call: ast.Call) -> str | None:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and node.value is call:
            if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
                return node.targets[0].id
    return None


def _escapes(tree: ast.AST, call: ast.Call) -> bool:
    """Is this call expression returned, yielded, or an argument?"""
    for node in ast.walk(tree):
        if isinstance(node, (ast.Return, ast.Yield)) and node.value is not None:
            if any(n is call for n in ast.walk(node.value)):
                return True
        if isinstance(node, ast.Call) and node is not call:
            for a in list(node.args) + [k.value for k in node.keywords]:
                if any(n is call for n in ast.walk(a)):
                    return True
    return False


# -- exception-path analysis -------------------------------------------------


@register_checker(
    "exception-path",
    "manual lock.acquire() followed by a raise-reachable call (fixpoint "
    "may-raise fact) before .release() without try/finally — the lock "
    "leaks on the exception path",
)
def check_exception_path(project: Project, f: SourceFile) -> list[Finding]:
    from kaspa_tpu.analysis.checkers import _site_for, walk_with_context

    out: list[Finding] = []
    graph = project.callgraph
    for node, cls, _fn in walk_with_context(f.tree):
        body = getattr(node, "body", None)
        if not isinstance(body, list):
            continue
        for i, stmt in enumerate(body):
            recv = _manual_acquire(stmt)
            if recv is None:
                continue
            # `x.acquire()` immediately wrapped in try/finally-with-release
            # is the blessed shape
            if i + 1 < len(body) and _protected_release(body[i + 1], recv):
                continue
            risky = _risky_before_release(body[i + 1 :], recv, graph, f.rel, cls)
            if risky is not None:
                out.append(
                    Finding(
                        f.rel, stmt.lineno, "exception-path",
                        f"{recv}.acquire() leaks on an exception path: "
                        f"{risky[1]} at line {risky[0]} can raise before "
                        f".release() — wrap in try/finally",
                    )
                )
    return out


def _manual_acquire(stmt: ast.AST) -> str | None:
    if (
        isinstance(stmt, ast.Expr)
        and isinstance(stmt.value, ast.Call)
        and isinstance(stmt.value.func, ast.Attribute)
        and stmt.value.func.attr == "acquire"
        and is_lock_expr(stmt.value.func.value)
    ):
        return _terminal_name(stmt.value.func.value)
    return None


def _protected_release(stmt: ast.AST, recv: str) -> bool:
    if not isinstance(stmt, ast.Try) or not stmt.finalbody:
        return False
    return any(_is_release(s, recv) for s in stmt.finalbody)


def _is_release(stmt: ast.AST, recv: str) -> bool:
    return (
        isinstance(stmt, ast.Expr)
        and isinstance(stmt.value, ast.Call)
        and isinstance(stmt.value.func, ast.Attribute)
        and stmt.value.func.attr == "release"
        and _terminal_name(stmt.value.func.value) == recv
    )


def _risky_before_release(stmts: list, recv: str, graph, rel: str, cls: str):
    """(line, what) of the first raise-reachable operation between the
    acquire and the matching release in this block, or None when the
    release never appears (released elsewhere — out of scope) or nothing
    risky sits in between."""
    from kaspa_tpu.analysis.checkers import _site_for

    risky = None
    saw_release = False
    for stmt in stmts:
        if _is_release(stmt, recv):
            saw_release = True
            break
        if risky is None:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Raise):
                    risky = (n.lineno, "explicit raise")
                    break
                if isinstance(n, ast.Call):
                    site = _site_for(n)
                    target = graph.resolve_site(site, rel, cls)
                    if target is not None and target.may_raise:
                        risky = (n.lineno, f"{site.name}() (may raise)")
                        break
    return risky if (saw_release and risky is not None) else None
