"""The graftlint per-file checkers (see package docstring for the catalog).

Each checker is registered under its id and returns findings for ONE
file; anything project-wide (the fixpoint call graph, the fault-point
catalog, the metric-name census) is computed once and cached on the
Project.  Checkers never import the modules they analyze — everything is
AST-only, so linting a file with a seeded deadlock cannot hang the lint.
(The two project-level checkers that DO import runtime modules — the
kernel-shape audit and the env-knob catalog — live in shapes.py and
envknobs.py and run once per project, the former only when gated on.)
"""

from __future__ import annotations

import ast
import re

from kaspa_tpu.analysis.blocking import (
    _terminal_name,
    _walk_shallow,
    blocking_reason,
    is_lock_expr,
)
from kaspa_tpu.analysis.callgraph import NO_EXPAND, CallSite, render_chain
from kaspa_tpu.analysis.core import Finding, Project, SourceFile, register_checker

# ----------------------------------------------------------------------
# 1. blocking-under-lock (fixpoint transitive expansion)
# ----------------------------------------------------------------------


def walk_with_context(tree: ast.AST):
    """Yield (node, enclosing_class_name, enclosing_function_ast) for every
    node — the resolution context the call graph needs at a use site."""
    stack = [(tree, "", None)]
    while stack:
        node, cls, fn = stack.pop()
        yield node, cls, fn
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                stack.append((child, child.name, fn))
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.append((child, cls, child))
            else:
                stack.append((child, cls, fn))


@register_checker(
    "blocking-under-lock",
    "device dispatch / Future.result / sleep / socket recv / thread join "
    "inside a `with <lock>` body, at ANY call depth (whole-program "
    "fixpoint expansion through the module-qualified call graph)",
)
def check_blocking_under_lock(project: Project, f: SourceFile) -> list[Finding]:
    out: list[Finding] = []
    graph = project.callgraph
    for node, cls, _fn in walk_with_context(f.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        lock_names = [
            _terminal_name(item.context_expr)
            for item in node.items
            if is_lock_expr(item.context_expr)
        ]
        if not lock_names:
            continue
        held = "/".join(lock_names)
        for inner in _body_calls(node):
            reason = blocking_reason(inner)
            name = _terminal_name(inner.func)
            if reason is not None:
                out.append(
                    Finding(
                        f.rel, inner.lineno, "blocking-under-lock",
                        f"{name}() while holding {held}: {reason}",
                    )
                )
                continue
            # transitive expansion: resolve the callee through the
            # module-qualified call graph; its fixpoint may-block fact
            # carries the full chain down to the primitive blocking call
            if name in NO_EXPAND or name.startswith("__"):
                continue
            site = _site_for(inner)
            target = graph.resolve_site(site, f.rel, cls)
            if target is not None and target.block_chain:
                out.append(
                    Finding(
                        f.rel, inner.lineno, "blocking-under-lock",
                        f"{name}() while holding {held} blocks transitively "
                        f"(depth {len(target.block_chain)}): "
                        f"{render_chain(target.block_chain)}",
                    )
                )
    return out


def _site_for(call: ast.Call) -> CallSite:
    name = _terminal_name(call.func)
    if isinstance(call.func, ast.Attribute):
        return CallSite(call.lineno, name, _terminal_name(call.func.value), True)
    return CallSite(call.lineno, name, "", False)


def _body_calls(with_node):
    """Call nodes lexically inside the with body (nested defs excluded)."""
    for stmt in with_node.body:
        for n in [stmt, *_walk_shallow(stmt)]:
            if isinstance(n, ast.Call):
                yield n


# ----------------------------------------------------------------------
# 2. raw-lock
# ----------------------------------------------------------------------


@register_checker(
    "raw-lock",
    "threading.Lock()/RLock()/bare Condition() construction outside "
    "utils/sync.py — use a ranked LockCtx (utils.sync.RANKS)",
)
def check_raw_lock(project: Project, f: SourceFile) -> list[Finding]:
    if f.rel.endswith("utils/sync.py"):
        return []  # the one module allowed to touch the primitives
    out = []
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and _terminal_name(fn.value) == "threading"):
            continue
        if fn.attr in ("Lock", "RLock"):
            out.append(
                Finding(
                    f.rel, node.lineno, "raw-lock",
                    f"raw threading.{fn.attr}() — construct a ranked LockCtx "
                    "(utils/sync.py) so the inversion detector covers this lock",
                )
            )
        elif fn.attr == "Condition" and not node.args:
            out.append(
                Finding(
                    f.rel, node.lineno, "raw-lock",
                    "bare threading.Condition() hides an unranked lock — build "
                    "it from a LockCtx via .condition()",
                )
            )
    return out


# ----------------------------------------------------------------------
# 3. tracer-hazard
# ----------------------------------------------------------------------

UNROLL_THRESHOLD = 64  # the PR 11 compile cliff: XLA:CPU goes superlinear


def _module_dict_names(tree: ast.Module) -> dict[str, int]:
    out: dict[str, int] = {}
    for stmt in tree.body:
        targets, value = [], None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None:
            continue
        is_dict = isinstance(value, (ast.Dict, ast.DictComp)) or (
            isinstance(value, ast.Call) and _terminal_name(value.func) == "dict"
        )
        if not is_dict:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out[t.id] = stmt.lineno
    return out


def _decorator_names(fn_node) -> list[str]:
    names = []
    for dec in fn_node.decorator_list:
        names.append(_terminal_name(dec))
        if isinstance(dec, ast.Call):
            for a in dec.args:  # partial(jax.jit, ...)
                names.append(_terminal_name(a))
    return [n for n in names if n]


def _jitted_functions(tree: ast.Module):
    """FunctionDef nodes whose bodies run under a JAX trace: decorated
    with jit/partial(jit) or passed by name to jit()/shard_map()."""
    defs: dict[str, list] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, []).append(node)
    jitted: dict[int, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            decs = _decorator_names(node)
            if "jit" in decs or "shard_map" in decs:
                jitted[id(node)] = node
        elif isinstance(node, ast.Call) and _terminal_name(node.func) in ("jit", "shard_map"):
            if node.args and isinstance(node.args[0], ast.Name):
                for fn in defs.get(node.args[0].id, []):
                    jitted[id(fn)] = fn
    return list(jitted.values())


def _lru_cached_names(tree: ast.Module) -> set[str]:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            if any(n in ("lru_cache", "cache") for n in _decorator_names(node)):
                out.add(node.name)
    return out


def _range_trip_count(call: ast.Call) -> int | None:
    if _terminal_name(call.func) != "range":
        return None
    vals = []
    for a in call.args:
        if isinstance(a, ast.Constant) and isinstance(a.value, int):
            vals.append(a.value)
        else:
            return None
    if len(vals) == 1:
        return vals[0]
    if len(vals) >= 2:
        step = vals[2] if len(vals) == 3 and vals[2] else 1
        return max(0, (vals[1] - vals[0]) // step)
    return None


@register_checker(
    "tracer-hazard",
    "module caches / lru_cache / host coercions / unrolled constant loops "
    "inside jit-traced function bodies (RewriteTracer poisoning, compile cliffs)",
)
def check_tracer_hazard(project: Project, f: SourceFile) -> list[Finding]:
    tree = f.tree
    if not isinstance(tree, ast.Module):
        return []
    dict_names = _module_dict_names(tree)
    lru_names = _lru_cached_names(tree)
    out: list[Finding] = []
    for fn in _jitted_functions(tree):
        local_args = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        for node in _walk_shallow(fn):
            if isinstance(node, ast.Name) and node.id in dict_names and node.id not in local_args:
                out.append(
                    Finding(
                        f.rel, node.lineno, "tracer-hazard",
                        f"jitted `{fn.name}` touches module-level dict `{node.id}` "
                        f"(defined line {dict_names[node.id]}): a trace can memoize "
                        "RewriteTracers into it, poisoning later calls",
                    )
                )
            elif isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name in lru_names:
                    out.append(
                        Finding(
                            f.rel, node.lineno, "tracer-hazard",
                            f"jitted `{fn.name}` calls lru_cache'd `{name}`: tracer "
                            "arguments poison the cache across traces",
                        )
                    )
                elif (
                    name in ("int", "float", "bool")
                    and isinstance(node.func, ast.Name)
                    and node.args
                    and not isinstance(node.args[0], ast.Constant)
                ):
                    out.append(
                        Finding(
                            f.rel, node.lineno, "tracer-hazard",
                            f"jitted `{fn.name}` coerces with {name}(): concretizes "
                            "a tracer (ConcretizationTypeError at best, silently "
                            "frozen constant at worst)",
                        )
                    )
                elif isinstance(node.func, ast.Attribute) and _root_name(node.func) in ("np", "numpy"):
                    out.append(
                        Finding(
                            f.rel, node.lineno, "tracer-hazard",
                            f"jitted `{fn.name}` calls {_root_name(node.func)}.{node.func.attr}: "
                            "numpy executes on host at trace time, not on device",
                        )
                    )
            elif isinstance(node, ast.For) and isinstance(node.iter, ast.Call):
                trips = _range_trip_count(node.iter)
                if trips is not None and trips >= UNROLL_THRESHOLD:
                    out.append(
                        Finding(
                            f.rel, node.lineno, "tracer-hazard",
                            f"jitted `{fn.name}` unrolls a {trips}-iteration Python "
                            f"loop (threshold {UNROLL_THRESHOLD}): XLA:CPU compile "
                            "time goes superlinear — use lax.scan/fori_loop",
                        )
                    )
    return out


def _root_name(attr: ast.Attribute) -> str:
    node: ast.AST = attr
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


# ----------------------------------------------------------------------
# 4. trace-ctx-handoff
# ----------------------------------------------------------------------

_INSTRUMENTED = ("pipeline/", "ingest/", "serving/", "fabric/", "ops/dispatch.py")
_HANDOFF_METHODS = ("put", "put_nowait", "send")


def _mentions_ctx(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "ctx" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "ctx" in n.attr.lower():
            return True
        if isinstance(n, ast.Call) and _terminal_name(n.func) == "context":
            return True
    return False


@register_checker(
    "trace-ctx-handoff",
    "queue .put/.send in instrumented subsystems must carry the "
    "flight-recorder trace context (the PR 7 connected-span-tree invariant)",
)
def check_trace_ctx_handoff(project: Project, f: SourceFile) -> list[Finding]:
    if not any(part in f.rel for part in _INSTRUMENTED):
        return []
    out = []
    for node in ast.walk(f.tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
            continue
        if node.func.attr not in _HANDOFF_METHODS or not node.args:
            continue
        payload = node.args[0]
        # only literal containers are checkable: packing fields into a
        # tuple/dict and forgetting the ctx is exactly the regression shape
        # that broke span-tree connectivity; an object payload is assumed
        # to carry its ctx as an attribute (Task.ctx, Notification.ctx)
        if not isinstance(payload, (ast.Tuple, ast.List, ast.Dict)):
            continue
        if _mentions_ctx(node):
            continue
        out.append(
            Finding(
                f.rel, node.lineno, "trace-ctx-handoff",
                f".{node.func.attr}() hands a literal payload across a queue "
                "boundary without a trace ctx: the consumer's spans detach "
                "from the block's tree (include the TraceContext in the payload)",
            )
        )
    return out


# ----------------------------------------------------------------------
# 5. registry-hygiene
# ----------------------------------------------------------------------

_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_METRIC_METHODS = ("counter", "counter_family", "histogram", "histogram_family", "gauge", "gauge_family")


def _hygiene_census(project: Project) -> dict:
    """Project-wide pass, computed once: fault points used vs declared,
    metric registrations by name."""
    cache = getattr(project, "_hygiene", None)
    if cache is not None:
        return cache
    used_points: dict[str, list[tuple[str, int]]] = {}
    metrics: dict[str, list[tuple[str, int]]] = {}
    collectors: dict[str, list[tuple[str, int]]] = {}
    declared: dict[str, int] = {}
    catalog_file = None
    for f in project.files:
        if f.rel.endswith("resilience/faults.py"):
            catalog_file = f.rel
            for node in ast.walk(f.tree):
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign):
                    targets, value = [node.target], node.value
                else:
                    continue
                if (
                    any(isinstance(t, ast.Name) and t.id == "FAULT_POINTS" for t in targets)
                    and isinstance(value, ast.Dict)
                ):
                    for k in value.keys:
                        if isinstance(k, ast.Constant) and isinstance(k.value, str):
                            declared[k.value] = k.lineno
        for node in ast.walk(f.tree):
            if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)):
                continue
            recv = _terminal_name(node.func.value)
            if node.func.attr == "fire" and recv == "FAULTS":
                if node.args and isinstance(node.args[0], ast.Constant) and isinstance(node.args[0].value, str):
                    used_points.setdefault(node.args[0].value, []).append((f.rel, node.lineno))
            elif recv == "REGISTRY" and node.func.attr in _METRIC_METHODS:
                if node.args and isinstance(node.args[0], ast.Constant) and isinstance(node.args[0].value, str):
                    metrics.setdefault(node.args[0].value, []).append((f.rel, node.lineno))
            elif recv == "REGISTRY" and node.func.attr == "register_collector":
                if node.args and isinstance(node.args[0], ast.Constant) and isinstance(node.args[0].value, str):
                    collectors.setdefault(node.args[0].value, []).append((f.rel, node.lineno))
    project._hygiene = {
        "used": used_points,
        "declared": declared,
        "catalog_file": catalog_file,
        "metrics": metrics,
        "collectors": collectors,
    }
    return project._hygiene


@register_checker(
    "registry-hygiene",
    "fault points used in code must appear in the resilience/faults.py "
    "FAULT_POINTS catalog and vice versa; metric names follow the "
    "snake_case convention and are registered exactly once",
)
def check_registry_hygiene(project: Project, f: SourceFile) -> list[Finding]:
    census = _hygiene_census(project)
    out: list[Finding] = []
    # fault-point checks only when the catalog module is in the lint set
    if census["catalog_file"] is not None:
        declared, used = census["declared"], census["used"]
        if f.rel == census["catalog_file"]:
            if not declared:
                out.append(
                    Finding(
                        f.rel, 1, "registry-hygiene",
                        "resilience/faults.py declares no FAULT_POINTS catalog "
                        "(dict literal of point name -> description)",
                    )
                )
            for point, line in declared.items():
                if point not in used:
                    out.append(
                        Finding(
                            f.rel, line, "registry-hygiene",
                            f"fault point {point!r} is cataloged but no FAULTS.fire "
                            "site uses it: delete the dead point",
                        )
                    )
        for point, sites in used.items():
            if point in declared:
                continue
            for rel, line in sites:
                if rel == f.rel:
                    out.append(
                        Finding(
                            f.rel, line, "registry-hygiene",
                            f"fault point {point!r} fired here is missing from the "
                            "FAULT_POINTS catalog in resilience/faults.py",
                        )
                    )
    # metric naming + duplicate registration
    for kind in ("metrics", "collectors"):
        for name, sites in census[kind].items():
            canonical = min(sites)
            for rel, line in sites:
                if rel != f.rel:
                    continue
                if not _METRIC_NAME_RE.match(name):
                    out.append(
                        Finding(
                            f.rel, line, "registry-hygiene",
                            f"metric name {name!r} violates the snake_case "
                            "convention ^[a-z][a-z0-9_]*$",
                        )
                    )
                if len(sites) > 1 and (rel, line) != canonical:
                    out.append(
                        Finding(
                            f.rel, line, "registry-hygiene",
                            f"duplicate registration of {name!r} (first at "
                            f"{canonical[0]}:{canonical[1]}): one name, one series",
                        )
                    )
    return out


# ----------------------------------------------------------------------
# 6. unbounded-queue
# ----------------------------------------------------------------------

_QUEUE_CTORS = ("Queue", "LifoQueue", "PriorityQueue")


def _is_unbounded_arg(node: ast.AST | None) -> bool:
    """A bound argument that is literally 0/None is no bound at all."""
    if node is None:
        return True
    return isinstance(node, ast.Constant) and node.value in (0, None)


@register_checker(
    "unbounded-queue",
    "deque()/queue.Queue() constructed without an explicit bound outside "
    "utils/ — every buffer in the node must state its overflow policy "
    "(maxlen/maxsize, a capacity check at the producer, or a justified pragma)",
)
def check_unbounded_queue(project: Project, f: SourceFile) -> list[Finding]:
    if f.rel.startswith("utils/") or "/utils/" in f.rel:
        return []  # primitives layer: sync.py's waiter deque etc. are leaf internals
    out: list[Finding] = []
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _terminal_name(node.func)
        if name == "deque":
            # deque(iterable, maxlen) — bounded iff maxlen is present and real
            maxlen = node.args[1] if len(node.args) >= 2 else None
            if maxlen is None:
                for kw in node.keywords:
                    if kw.arg == "maxlen":
                        maxlen = kw.value
            if _is_unbounded_arg(maxlen):
                out.append(
                    Finding(
                        f.rel, node.lineno, "unbounded-queue",
                        "deque() without maxlen: under sustained overload this "
                        "buffer grows until the process dies — bound it, enforce "
                        "a capacity check at the producer, or pragma with the "
                        "reason it cannot overflow",
                    )
                )
        elif name in _QUEUE_CTORS:
            maxsize = node.args[0] if node.args else None
            if maxsize is None:
                for kw in node.keywords:
                    if kw.arg == "maxsize":
                        maxsize = kw.value
            if _is_unbounded_arg(maxsize):
                out.append(
                    Finding(
                        f.rel, node.lineno, "unbounded-queue",
                        f"{name}() without maxsize: an unbounded handoff queue "
                        "turns overload into memory exhaustion — give it a "
                        "maxsize and an overflow policy, or pragma with the "
                        "reason the producer is naturally bounded",
                    )
                )
        elif name == "SimpleQueue":
            out.append(
                Finding(
                    f.rel, node.lineno, "unbounded-queue",
                    "SimpleQueue() has no bound at all — use Queue(maxsize=...) "
                    "with an overflow policy, or pragma with the reason the "
                    "producer is naturally bounded",
                )
            )
    return out
