"""Whole-program call graph + fixpoint fact propagation (the v2 engine).

The v1 engine expanded calls exactly one hop through a bare-name index:
a blocking call buried two frames deep was invisible.  This module builds
a *module-qualified* call graph over the whole lint set and runs fixpoint
transitive propagation of dataflow facts over it, so a checker asking
"can this call block?" gets an answer of any depth, with the full call
chain as evidence.

Resolution strategy (precision over recall, ambiguity tracked — never
silently guessed):

1. bare ``name(...)``   -> a def in the same module, else a ``from x
   import name`` target resolved through the import table, else the
   unique project-wide definition of that bare name;
2. ``mod.name(...)``    -> module-level def in the module the alias
   imports (``import kaspa_tpu.ops.mesh as mod`` / ``from kaspa_tpu.ops
   import mesh``);
3. ``self.name(...)`` / ``cls.name(...)`` -> the method in the enclosing
   class (same module);
4. ``recv.name(...)``   -> the unique method of that name across every
   class in the project; when several classes define it, receiver-name
   heuristics narrow the field (a receiver called ``ticket`` selects a
   class named ``Ticket``); anything still plural is recorded as an
   *ambiguous* site — counted, reported in the LINT.json callgraph
   section, and never expanded.

Facts propagated to fixpoint (monotone booleans, BFS over reverse edges
so cycles and mutual recursion terminate and every chain is shortest):

- ``may-block``: seeded from :func:`blocking.direct_blocking_calls`;
  each infected node carries the hop-by-hop chain down to the primitive
  blocking call for the finding message.
- ``may-raise``: seeded from explicit ``raise`` statements; drives the
  exception-path analysis in the lifecycle checker.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from kaspa_tpu.analysis.blocking import (
    _terminal_name,
    _walk_shallow,
    direct_blocking_calls,
)

# bare names never worth resolving even when unique project-wide: tiny
# accessors and stdlib look-alikes dominate, and an expansion through one
# of these is noise, not evidence
NO_EXPAND = {
    "get", "set", "len", "items", "keys", "values", "append", "pop",
    "int", "str", "float", "bool", "list", "dict", "tuple", "print",
    "isinstance", "getattr", "setattr", "hasattr", "range", "min", "max",
}


# camelCase / digit-run word splitter for receiver-name narrowing
_WORD_RE = re.compile(r"[A-Z]+(?![a-z])|[A-Z]?[a-z0-9]+")


def _words_align(recv: str, cls: str) -> bool:
    """True when receiver and class name share a word-boundary-anchored
    stem: some word of one is a prefix of some word of the other."""
    rwords = [w for w in recv.split("_") if w]
    cwords = [w.lower() for w in _WORD_RE.findall(cls)]
    return any(
        cw.startswith(rw) or rw.startswith(cw)
        for rw in rwords
        for cw in cwords
    )


@dataclass
class CallSite:
    """One call expression inside a function body."""

    line: int
    name: str  # terminal callee name ("dispatch" for self.eng.dispatch())
    recv: str  # terminal receiver name ("eng"), "" for bare calls
    is_attr: bool
    target: "FuncNode | None" = None  # resolved callee
    candidates: tuple = ()  # qnames when ambiguous (len > 1, unresolved)


@dataclass
class FuncNode:
    """A module-qualified function/method definition."""

    qname: str  # "kaspa_tpu/ops/dispatch.py::Ticket.wait"
    name: str
    rel: str
    cls: str  # enclosing class name, "" for module-level defs
    lineno: int
    node: ast.AST
    blocking: list = field(default_factory=list)  # [(line, reason)] direct
    raises: bool = False  # contains an explicit `raise` (lexically)
    sites: list = field(default_factory=list)  # [CallSite]
    callers: list = field(default_factory=list)  # [(FuncNode, CallSite)]
    # fixpoint facts
    block_chain: list | None = None  # [{"rel","line","what"}], last = reason
    may_raise: bool = False


def _module_of(rel: str) -> str:
    """Repo-relative path -> dotted module ("a/b/c.py" -> "a.b.c")."""
    mod = rel[:-3] if rel.endswith(".py") else rel
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


def _direct_raises(fn_node: ast.AST) -> bool:
    return any(isinstance(n, ast.Raise) for n in _walk_shallow(fn_node))


def _collect_sites(fn_node: ast.AST) -> list[CallSite]:
    out = []
    for n in _walk_shallow(fn_node):
        if not isinstance(n, ast.Call):
            continue
        name = _terminal_name(n.func)
        if not name or name.startswith("__"):
            continue
        if isinstance(n.func, ast.Attribute):
            out.append(CallSite(n.lineno, name, _terminal_name(n.func.value), True))
        else:
            out.append(CallSite(n.lineno, name, "", False))
    return out


class CallGraph:
    """Project-wide call graph with resolved edges and fixpoint facts."""

    def __init__(self, files):
        self.files = files
        self.nodes: list[FuncNode] = []
        # (module, name) -> module-level FuncNode
        self.module_defs: dict[tuple[str, str], FuncNode] = {}
        # (module, Class, name) -> method FuncNode
        self.methods: dict[tuple[str, str, str], FuncNode] = {}
        # bare name -> [FuncNode] across the project (defs + methods)
        self.bare: dict[str, list[FuncNode]] = {}
        # method name -> [FuncNode] (methods only, for receiver heuristics)
        self.method_index: dict[str, list[FuncNode]] = {}
        # per-module import tables
        self._mod_alias: dict[str, dict[str, str]] = {}  # alias -> dotted module
        self._sym_alias: dict[str, dict[str, tuple[str, str]]] = {}  # alias -> (module, symbol)
        self._modules: set[str] = set()
        self.ambiguous_sites = 0
        self.resolved_sites = 0
        self._build()
        self._resolve_all()
        self._fixpoint()

    # -- construction -------------------------------------------------------

    def _build(self) -> None:
        for f in self.files:
            mod = _module_of(f.rel)
            self._modules.add(mod)
            self._mod_alias[mod] = {}
            self._sym_alias[mod] = {}
            self._collect_imports(f.tree, mod)
            self._collect_defs(f, mod, f.tree, cls="", prefix="")

    def _collect_imports(self, tree: ast.AST, mod: str) -> None:
        pkg = mod.rsplit(".", 1)[0] if "." in mod else ""
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    # `import a.b.c` binds `a`; `import a.b.c as m` binds a.b.c
                    self._mod_alias[mod][alias] = a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.names:
                base = node.module or ""
                if node.level:  # relative import: resolve against this package
                    parts = pkg.split(".") if pkg else []
                    parts = parts[: len(parts) - (node.level - 1)] if node.level > 1 else parts
                    base = ".".join(parts + ([node.module] if node.module else []))
                for a in node.names:
                    alias = a.asname or a.name
                    # `from pkg import mod` is a module alias when pkg.mod
                    # is in the lint set, a symbol import otherwise
                    if f"{base}.{a.name}" in self._modules or self._looks_like_module(base, a.name):
                        self._mod_alias[mod][alias] = f"{base}.{a.name}"
                    else:
                        self._sym_alias[mod][alias] = (base, a.name)

    def _looks_like_module(self, base: str, name: str) -> bool:
        dotted = f"{base}.{name}"
        return any(f.rel in (dotted.replace(".", "/") + ".py", dotted.replace(".", "/") + "/__init__.py") for f in self.files)

    def _collect_defs(self, f, mod: str, tree: ast.AST, cls: str, prefix: str) -> None:
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, ast.ClassDef):
                self._collect_defs(f, mod, node, cls=node.name, prefix=prefix)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local = f"{prefix}{cls + '.' if cls else ''}{node.name}"
                fn = FuncNode(
                    qname=f"{f.rel}::{local}",
                    name=node.name,
                    rel=f.rel,
                    cls=cls,
                    lineno=node.lineno,
                    node=node,
                    blocking=direct_blocking_calls(node),
                    raises=_direct_raises(node),
                    sites=_collect_sites(node),
                )
                self.nodes.append(fn)
                self.bare.setdefault(node.name, []).append(fn)
                if cls:
                    self.methods.setdefault((mod, cls, node.name), fn)
                    self.method_index.setdefault(node.name, []).append(fn)
                else:
                    self.module_defs.setdefault((mod, node.name), fn)
                # nested defs become their own nodes (they run later,
                # elsewhere — their calls must not leak into the parent)
                self._collect_defs(f, mod, node, cls="", prefix=f"{local}.")

    # -- resolution ---------------------------------------------------------

    def resolve_site(self, site: CallSite, rel: str, cls: str) -> "FuncNode | None":
        """Resolve one call site in the context of (file, enclosing class).
        Returns the target, or None (ambiguity lands in site.candidates)."""
        if site.name in NO_EXPAND:
            return None
        mod = _module_of(rel)
        if not site.is_attr:
            hit = self.module_defs.get((mod, site.name))
            if hit is not None:
                return hit
            sym = self._sym_alias.get(mod, {}).get(site.name)
            if sym is not None:
                hit = self.module_defs.get((sym[0], sym[1]))
                if hit is not None:
                    return hit
            return self._unique_bare(site)
        # attribute call
        if site.recv in ("self", "cls") and cls:
            hit = self.methods.get((mod, cls, site.name))
            if hit is not None:
                return hit
        target_mod = self._mod_alias.get(mod, {}).get(site.recv)
        if target_mod is not None:
            return self.module_defs.get((target_mod, site.name))
        return self._method_heuristic(site)

    def _unique_bare(self, site: CallSite) -> "FuncNode | None":
        infos = self.bare.get(site.name, [])
        if len(infos) == 1:
            return infos[0]
        if len(infos) > 1:
            site.candidates = tuple(n.qname for n in infos)
        return None

    def _method_heuristic(self, site: CallSite) -> "FuncNode | None":
        cands = self.method_index.get(site.name, [])
        if len(cands) == 1:
            return cands[0]
        if not cands:
            return None
        # receiver-name narrowing: `ticket.wait()` selects class Ticket.
        # Both directions run, aligned at word boundaries (receiver
        # "admission" vs class AdmissionTicket; receiver "tier" vs class
        # IngestTier); exact match wins outright.  Matches must anchor at
        # the start of a camelCase / snake_case word — a raw substring
        # test accepts accidents that straddle word boundaries (receiver
        # "db" inside "Sharde|dB|roadcaster") and misresolves the site.
        rl = site.recv.lower().strip("_")
        if rl:
            exact = [c for c in cands if c.cls.lower() == rl]
            if len(exact) == 1:
                return exact[0]
            subs = [c for c in cands if _words_align(rl, c.cls)]
            if len(subs) == 1:
                return subs[0]
            if subs:
                cands = subs
        site.candidates = tuple(sorted(c.qname for c in cands))
        return None

    def _resolve_all(self) -> None:
        for fn in self.nodes:
            for site in fn.sites:
                target = self.resolve_site(site, fn.rel, fn.cls)
                if target is not None:
                    site.target = target
                    target.callers.append((fn, site))
                    self.resolved_sites += 1
                elif site.candidates:
                    self.ambiguous_sites += 1

    # -- fixpoint -----------------------------------------------------------

    def _fixpoint(self) -> None:
        # may-block: BFS from direct blockers over reverse edges.  A node's
        # fact is set exactly once (first = shortest chain), so recursion
        # cycles and mutual recursion terminate trivially.
        queue = []
        for fn in self.nodes:
            if fn.blocking:
                line, reason = fn.blocking[0]
                fn.block_chain = [{"rel": fn.rel, "line": line, "what": reason}]
                queue.append(fn)
        i = 0
        while i < len(queue):
            g = queue[i]
            i += 1
            for caller, site in g.callers:
                if caller.block_chain is None:
                    caller.block_chain = [
                        {"rel": caller.rel, "line": site.line, "what": f"{site.name}()"}
                    ] + g.block_chain
                    queue.append(caller)
        # may-raise: same propagation, boolean only
        queue = [fn for fn in self.nodes if fn.raises]
        for fn in queue:
            fn.may_raise = True
        i = 0
        while i < len(queue):
            g = queue[i]
            i += 1
            for caller, _site in g.callers:
                if not caller.may_raise:
                    caller.may_raise = True
                    queue.append(caller)

    # -- queries ------------------------------------------------------------

    def node_for(self, rel: str, fn_ast: ast.AST) -> "FuncNode | None":
        for fn in self.nodes:
            if fn.rel == rel and fn.node is fn_ast:
                return fn
        return None

    def stats(self) -> dict:
        return {
            "functions": len(self.nodes),
            "resolved_edges": self.resolved_sites,
            "ambiguous_sites": self.ambiguous_sites,
            "may_block": sum(1 for n in self.nodes if n.block_chain),
            "may_raise": sum(1 for n in self.nodes if n.may_raise),
        }


def render_chain(chain: list) -> str:
    """Human form of a may-block chain: "a.py:12 f() -> b.py:9 sleep..."."""
    return " -> ".join(f"{h['rel']}:{h['line']} {h['what']}" for h in chain)
