"""Shared AST heuristics: what blocks, and what is a lock.

One vocabulary for both the blocking-under-lock checker and the one-hop
call-graph table, so "blocking" means the same thing at depth 0 and
depth 1.  Everything here is a lexical heuristic tuned to THIS repo's
naming conventions (documented in README "Static analysis"); pragmas are
the escape hatch, not special cases in the matcher.
"""

from __future__ import annotations

import ast

# device-dispatch entry points: one of these inside a lock body means a
# jit compile or an XLA execution can serialize every other lock waiter
# behind the device (the PR 8 / PR 12 bug class)
DEVICE_CALLS = {
    "verify_batch",
    "host_verify_batch",
    "block_until_ready",
    "device_put",
    "dryrun_multichip",
}

# receivers that name a condition variable: .wait() on these RELEASES the
# lock (that is the point of a condvar) and is exempt; .wait() on
# anything else (Event, Ticket, Future) keeps the lock held while parked
_CONDITION_HINTS = ("cv", "cond", "wake", "idle", "empty", "full", "nonempty")

# with-item names that denote a lock / mutex guard
_LOCK_NAME_HINTS = ("lock", "mutex")


def _terminal_name(node: ast.AST) -> str:
    """x -> "x"; a.b.c -> "c"; f(...) -> f's terminal name; else ""."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Call):
        return _terminal_name(node.func)
    return ""


def is_lock_expr(node: ast.AST) -> bool:
    """Does this with-item expression look like a lock guard?"""
    name = _terminal_name(node).lower()
    if not name:
        return False
    if isinstance(node, ast.Call) and name in ("lockctx", "ranked_lock"):
        return True
    if any(h in name for h in _LOCK_NAME_HINTS):
        return True
    # bare mutex names: _mu / mu / commit_mu ... and condvar guards (a
    # `with self._cv:` holds the underlying lock exactly like `with mu:`)
    stripped = name.strip("_")
    if stripped == "mu" or name.endswith("_mu") or name.endswith("mu"):
        return True
    return any(stripped == h or name.endswith("_" + h) for h in ("cv", "cond"))


def _is_condition_receiver(node: ast.AST) -> bool:
    name = _terminal_name(node).lower()
    return any(h in name for h in _CONDITION_HINTS)


def _numeric_const(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, (int, float))


def blocking_reason(call: ast.Call) -> str | None:
    """Why this call blocks, or None.  The single source of truth for the
    blocking-under-lock bug class."""
    fn = call.func
    name = _terminal_name(fn)
    if not name:
        return None
    # time.sleep / bare sleep
    if name == "sleep":
        return "time.sleep blocks every waiter on the held lock"
    # Future / DispatchHandle result
    if name == "result" and isinstance(fn, ast.Attribute):
        return ".result() parks on a device/worker future"
    # synchronous verify dispatch (the historical pipeline.virtual bug)
    if name == "dispatch" and isinstance(fn, ast.Attribute):
        return ".dispatch() runs a device round-trip synchronously"
    if name in DEVICE_CALLS:
        return f"{name}() enters the device runtime (jit compile / XLA dispatch)"
    # socket reads
    if name in ("recv", "recvfrom", "recv_into", "accept") and isinstance(fn, ast.Attribute):
        return f".{name}() blocks on the network"
    # thread joins: obj.join() / obj.join(timeout).  str.join(iterable) and
    # os.path.join(...) take non-numeric arguments and are skipped.
    if name == "join" and isinstance(fn, ast.Attribute):
        if isinstance(fn.value, ast.Constant):
            return None  # ", ".join(...)
        if _terminal_name(fn.value) in ("path", "posixpath", "ntpath"):
            return None  # os.path.join
        args_ok = not call.args or (len(call.args) == 1 and _numeric_const(call.args[0]))
        kw_ok = all(k.arg == "timeout" for k in call.keywords)
        if args_ok and kw_ok:
            return ".join() waits for a thread"
        return None
    # parked waits that do NOT release the lock (Event/Ticket/Future.wait);
    # condvar waits are exempt by receiver-name convention
    if name in ("wait", "wait_for") and isinstance(fn, ast.Attribute):
        if _is_condition_receiver(fn.value):
            return None
        return f".{name}() parks the thread without releasing the lock"
    return None


def direct_blocking_calls(fn_node: ast.AST) -> list[tuple[int, str]]:
    """(line, reason) for every blocking call lexically inside this
    function body (nested defs excluded — they run later, elsewhere)."""
    out: list[tuple[int, str]] = []
    for node in _walk_shallow(fn_node):
        if isinstance(node, ast.Call):
            reason = blocking_reason(node)
            if reason is not None:
                out.append((node.lineno, reason))
    return out


def _walk_shallow(root: ast.AST):
    """ast.walk, but do not descend into nested function/class defs."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def called_names(body_nodes) -> list[tuple[int, str]]:
    """(line, bare name) of every call in the given statement list, again
    without descending into nested defs."""
    out = []
    for stmt in body_nodes:
        for node in [stmt, *_walk_shallow(stmt)]:
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name:
                    out.append((node.lineno, name))
    return out
