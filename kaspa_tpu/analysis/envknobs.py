"""env-knob coherence: every ``KASPA_TPU_*`` site has a catalog row.

The runtime has grown ~90 ``KASPA_TPU_*`` environment reads across 20+
files; nothing ties them together, so knobs drift (two call sites reading
the same variable with *different* literal defaults is a live bug class —
the breaker threshold did exactly that).  This checker extracts a census
of every knob site from the ASTs and reconciles it against the committed
``KNOBS.md`` catalog at the lint root:

- a knob read in code but absent from KNOBS.md  → finding at the read
- a KNOBS.md row whose knob no longer has a site → finding at the row
- a site whose literal default differs from the catalog default → finding
- a catalog row with an empty Doc cell → finding (the catalog exists so
  an operator can grep one file; an undocumented row defeats that)

Dynamic names built from f-strings (``f"KASPA_TPU_WATCHDOG_{tier}_S"``)
are censused with ``*`` in place of each interpolated piece and matched
against a catalog row spelled the same way.

``tools/lint.py --knobs`` regenerates KNOBS.md from the census,
preserving hand-written Doc cells, so the fix for a stale catalog is one
command.
"""

from __future__ import annotations

import ast
import os
import re

from kaspa_tpu.analysis.core import Finding, Project, register_project_checker

_KNOB_RE = re.compile(r"^KASPA_TPU_[A-Z0-9_]+$")
_ROW_RE = re.compile(r"^\|\s*`([A-Z0-9_*]+)`\s*\|\s*(.*?)\s*\|\s*`?(.*?)`?\s*\|\s*(.*?)\s*\|\s*$")


def _knob_name(node: ast.AST) -> str | None:
    """The knob named by this expression: a literal, or an f-string with
    ``*`` standing in for interpolated pieces."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if _KNOB_RE.match(node.value) else None
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("*")
        name = "".join(parts)
        return name if name.startswith("KASPA_TPU_") else None
    return None


def _env_site(node: ast.AST):
    """(knob, default-repr | None, kind) for an environment access node."""
    # os.environ.get("K", default) / os.getenv("K", default) / env.get("K")
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        attr = node.func.attr
        if attr in ("get", "getenv", "pop", "setdefault") and node.args:
            knob = _knob_name(node.args[0])
            if knob is not None:
                default = None
                # only get/getenv fallbacks are knob *defaults* (a pop(k,
                # None) sentinel is cleanup, not configuration)
                if (
                    attr in ("get", "getenv")
                    and len(node.args) > 1
                    and isinstance(node.args[1], ast.Constant)
                    and node.args[1].value is not None
                ):
                    default = repr(node.args[1].value)
                return knob, default, "read"
    # os.environ["K"] — read or write; both count as sites
    if isinstance(node, ast.Subscript):
        knob = _knob_name(node.slice)
        if knob is not None:
            return knob, None, "index"
    return None


def scan_knob_sites(project: Project) -> dict[str, list[dict]]:
    """{knob: [{"rel", "line", "default"}...]} across the lint file set."""
    census: dict[str, list[dict]] = {}
    for f in project.files:
        for node in ast.walk(f.tree):
            site = _env_site(node)
            if site is None:
                continue
            knob, default, _kind = site
            census.setdefault(knob, []).append(
                {"rel": f.rel, "line": node.lineno, "default": default}
            )
    for sites in census.values():
        sites.sort(key=lambda s: (s["rel"], s["line"]))
    return census


def _owner(sites: list[dict]) -> str:
    """Owning module: the file providing a literal default, else the
    first site."""
    for s in sites:
        if s["default"] is not None:
            return s["rel"]
    return sites[0]["rel"]


def _catalog_default(sites: list[dict]) -> str:
    """The most common literal default across sites (ties break on first
    appearance); em-dash when no site supplies one."""
    tally: dict[str, int] = {}
    for s in sites:
        if s["default"] is not None:
            tally[s["default"]] = tally.get(s["default"], 0) + 1
    if not tally:
        return "—"
    best = max(tally.values())
    for s in sites:
        if s["default"] is not None and tally[s["default"]] == best:
            return f"`{s['default']}`"
    return "—"


def parse_knobs_md(text: str) -> dict[str, dict]:
    """{knob: {"line", "default", "owner", "doc"}} from KNOBS.md rows."""
    out: dict[str, dict] = {}
    for i, raw in enumerate(text.splitlines(), start=1):
        m = _ROW_RE.match(raw)
        if m is None or set(m.group(1)) <= {"-"}:
            continue
        knob = m.group(1)
        if not knob.startswith("KASPA_TPU_"):
            continue
        out[knob] = {
            "line": i,
            "default": m.group(2).strip(),
            "owner": m.group(3).strip(),
            "doc": m.group(4).strip(),
        }
    return out


def render_knobs_md(census: dict[str, list[dict]], existing_text: str | None) -> str:
    """The full KNOBS.md document; Doc cells survive regeneration."""
    docs = {}
    prior_defaults = {}
    if existing_text:
        rows = parse_knobs_md(existing_text)
        docs = {k: row["doc"] for k, row in rows.items()}
        prior_defaults = {k: row["default"] for k, row in rows.items()}
    lines = [
        "# KNOBS.md — `KASPA_TPU_*` environment knobs",
        "",
        "Generated by `python tools/lint.py --knobs` from the env-knob census;",
        "the Doc column is hand-written and survives regeneration.  The",
        "`env-knob` checker fails the lint gate when this file and the code",
        "disagree (unknown knob, dead row, conflicting defaults, empty doc).",
        "",
        "| Knob | Default | Owner | Doc |",
        "|------|---------|-------|-----|",
    ]
    for knob in sorted(census):
        sites = census[knob]
        # a committed default that is still observed at some site stays
        # (site-default conflicts are resolved by choosing the committed
        # one and pragma-ing the divergent site; don't flip-flop on regen)
        observed = {f"`{s['default']}`" for s in sites if s["default"] is not None}
        default = prior_defaults.get(knob)
        if default not in observed:
            default = _catalog_default(sites)
        lines.append(f"| `{knob}` | {default} | `{_owner(sites)}` | {docs.get(knob, '')} |")
    return "\n".join(lines) + "\n"


@register_project_checker(
    "env-knob",
    "every KASPA_TPU_* environment read appears in KNOBS.md with a "
    "matching default and a doc line, and every cataloged knob still has "
    "a site (regen: tools/lint.py --knobs)",
)
def check_env_knobs(project: Project):
    census = scan_knob_sites(project)
    knobs_path = os.path.join(project.root, "KNOBS.md")
    catalog: dict[str, dict] = {}
    if os.path.isfile(knobs_path):
        with open(knobs_path, encoding="utf-8") as fh:
            catalog = parse_knobs_md(fh.read())

    findings: list[Finding] = []
    payload = {
        "knobs": len(census),
        "sites": sum(len(v) for v in census.values()),
        "cataloged": len(catalog),
    }
    if not census and not catalog:
        return findings, payload  # project doesn't use env knobs at all

    for knob, sites in sorted(census.items()):
        row = catalog.get(knob)
        if row is None:
            s = sites[0]
            findings.append(
                Finding(
                    s["rel"], s["line"], "env-knob",
                    f"{knob} is read here but missing from KNOBS.md — run "
                    "`python tools/lint.py --knobs` and document it",
                )
            )
            continue
        if not row["doc"]:
            findings.append(
                Finding(
                    "KNOBS.md", row["line"], "env-knob",
                    f"{knob} has an empty Doc cell — one line on what it tunes",
                )
            )
        # the committed row is the truth a site must match; a divergence
        # pragma'd at one site must not re-flag the canonical one
        expected = row["default"] if row["default"] not in ("", "—") else _catalog_default(sites)
        for s in sites:
            if s["default"] is not None and f"`{s['default']}`" != expected:
                findings.append(
                    Finding(
                        s["rel"], s["line"], "env-knob",
                        f"{knob} read with default {s['default']} here but "
                        f"{expected} elsewhere/in KNOBS.md — one knob, one "
                        "default (or pragma the deliberate divergence)",
                    )
                )
    for knob, row in sorted(catalog.items()):
        if knob not in census:
            findings.append(
                Finding(
                    "KNOBS.md", row["line"], "env-knob",
                    f"{knob} is cataloged but no longer read anywhere in the "
                    "lint set — delete the row (or the knob regressed)",
                )
            )
    return findings, payload
