"""kernel-shape audit: the closed world of compiled shapes, checked.

Gated project checker (``--shapes`` / ``options={"kernel-shape": True}``)
— it imports jax and the real kernels, so it only runs when the caller
asks (the repo lint wrapper turns it on; the generic
``python -m kaspa_tpu.analysis`` CLI leaves it off for arbitrary trees).

Three failure classes, all anchored to committed source so pragmas and
the ratchet apply:

1. **dtype/shape drift** — every reachable (family, bucket, mesh)
   signature from ``ops/kernel_catalog.py`` is audited via
   ``jax.eval_shape`` on a minimal representative set of traces (see
   ``kernel_catalog.audit_all``: tracing is seconds per kernel, and the
   graph is identical across batch widths); a verify kernel that stops
   returning a ``[b] bool`` mask, or an aggregate partial that changes
   layout, fails lint before it fails a device batch.
2. **coverage holes** — a reachable signature matched by no
   ``WARM_COVERAGE`` rule: the shape would compile cold in production
   with no pretrace replaying it.
3. **dead rules** — a coverage rule matching no reachable signature:
   the rule (or the bucket ladder) rotted.

The audit is abstract evaluation only: no kernel compiles, no device
memory, which is what keeps ``roundcheck --only lint`` inside its 60 s
wall.
"""

from __future__ import annotations

from kaspa_tpu.analysis.core import Finding, Project, register_project_checker

_CATALOG_REL = "kaspa_tpu/ops/kernel_catalog.py"

_FAMILY_OWNERS = {
    "ladder": "kaspa_tpu/ops/secp256k1/verify.py",
    "ecdsa": "kaspa_tpu/ops/secp256k1/verify.py",
    "aggregate": "kaspa_tpu/ops/secp256k1/aggregate.py",
    "muhash": "kaspa_tpu/ops/muhash_ops.py",
}


def _anchor(project: Project, rel: str, symbol: str) -> tuple[str, int]:
    """(rel, line) of ``symbol`` in ``rel`` when it's in the lint set,
    else line 1 — findings stay pragma-able where possible."""
    f = project.by_rel(rel)
    if f is not None:
        for i, raw in enumerate(f.lines, start=1):
            if symbol in raw:
                return f.rel, i
        return f.rel, 1
    return rel, 1


@register_project_checker(
    "kernel-shape",
    "every reachable kernel family x bucket x mesh signature eval_shapes "
    "cleanly (dtype/shape drift) and is matched by a WARM_COVERAGE "
    "pretrace rule, with no dead rules (gated: imports jax)",
    gated=True,
)
def check_kernel_shapes(project: Project):
    from kaspa_tpu.ops import kernel_catalog as cat

    findings: list[Finding] = []
    rows = cat.enumerate_signatures()
    drift, traces = cat.audit_all(rows)
    for row, err in drift:
        rel, line = _anchor(
            project, _FAMILY_OWNERS.get(row["family"], _CATALOG_REL), "_kernel"
        )
        findings.append(
            Finding(
                rel, line, "kernel-shape",
                f"{row['family']}/{row['kernel']} bucket={row['bucket']} "
                f"mesh={row['mesh']}: {err}",
            )
        )
    for row in rows:
        if not cat.covered(row["family"], row["bucket"]):
            rel, line = _anchor(project, _CATALOG_REL, "WARM_COVERAGE")
            findings.append(
                Finding(
                    rel, line, "kernel-shape",
                    f"reachable shape {row['family']}/{row['kernel']} "
                    f"bucket={row['bucket']} is matched by no WARM_COVERAGE "
                    "rule — it would compile cold with no pretrace",
                )
            )
    reachable = {(r["family"], r["bucket"]) for r in rows}
    for fam, lo, hi in cat.WARM_COVERAGE:
        if not any(f == fam and lo <= b <= hi for f, b in reachable):
            rel, line = _anchor(project, _CATALOG_REL, "WARM_COVERAGE")
            findings.append(
                Finding(
                    rel, line, "kernel-shape",
                    f"dead WARM_COVERAGE rule ({fam!r}, {lo}, {hi}): matches "
                    "no reachable signature",
                )
            )
    payload = {
        "signatures": len(rows),
        "families": sorted({r["family"] for r in rows}),
        "audited": len(rows),
        "traces": traces,
        "drift_errors": len(drift),
        "coverage_rules": len(cat.WARM_COVERAGE),
    }
    return findings, payload
