"""graftlint core: findings, pragmas, the checker registry, project model.

Checkers are pure functions over parsed sources: ``check(project, file)
-> list[Finding]``.  The Project owns the file set and a lazily built
package-wide function index (the one-hop call graph the blocking checker
expands through).  Pragma handling lives here so every checker inherits
the same suppression semantics:

    x = threading.Lock()  # graftlint: allow(raw-lock) -- leaf metric guard

    # graftlint: allow(blocking-under-lock) -- cold path, bounded 50ms
    with self._mu:
        time.sleep(0.05)

A pragma suppresses matching findings on its own line; a pragma on a
comment-only line covers the next source line.  The justification (text
after ``--``/``—``) is mandatory: an allow() without one produces a
``pragma`` finding that cannot itself be suppressed.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

SEVERITY_ERROR = "error"
SEVERITY_WARN = "warn"

_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*allow\(\s*([a-z0-9_\-]+(?:\s*,\s*[a-z0-9_\-]+)*)\s*\)"
    r"\s*(?:(?:--|—|–)\s*(.*?))?\s*$"
)


@dataclass
class Finding:
    path: str  # repo-relative, stable across machines
    line: int
    checker: str
    message: str
    severity: str = SEVERITY_ERROR
    justification: str = ""  # filled when a pragma suppresses this finding

    def key(self) -> tuple:
        return (self.path, self.line, self.checker, self.message)

    def as_dict(self) -> dict:
        out = {
            "path": self.path,
            "line": self.line,
            "checker": self.checker,
            "severity": self.severity,
            "message": self.message,
        }
        if self.justification:
            out["justification"] = self.justification
        return out

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


@dataclass
class _Pragma:
    line: int
    checkers: tuple[str, ...]
    justification: str
    covers_next: bool  # comment-only line: applies to the following line


@dataclass
class SourceFile:
    path: str  # absolute
    rel: str  # relative to the lint root (posix separators)
    text: str
    tree: ast.AST
    pragmas: list[_Pragma] = field(default_factory=list)

    @property
    def lines(self) -> list[str]:
        return self.text.splitlines()

    def allow_for(self, line: int, checker: str) -> _Pragma | None:
        """The pragma suppressing ``checker`` at ``line``, if any."""
        for p in self.pragmas:
            if checker not in p.checkers and "all" not in p.checkers:
                continue
            if p.line == line or (p.covers_next and p.line + 1 == line):
                return p
        return None


def _parse_pragmas(text: str) -> list[_Pragma]:
    out = []
    for i, raw in enumerate(text.splitlines(), start=1):
        m = _PRAGMA_RE.search(raw)
        if m is None:
            continue
        ids = tuple(s.strip() for s in m.group(1).split(","))
        just = (m.group(2) or "").strip()
        covers_next = raw.strip().startswith("#")
        out.append(_Pragma(i, ids, just, covers_next))
    return out


# ----------------------------------------------------------------------
# checker registry
# ----------------------------------------------------------------------

CHECKERS: dict[str, "CheckerSpec"] = {}


@dataclass
class CheckerSpec:
    id: str
    description: str
    fn: object  # (project, file) -> list[Finding]


def register_checker(checker_id: str, description: str):
    def deco(fn):
        CHECKERS[checker_id] = CheckerSpec(checker_id, description, fn)
        return fn

    return deco


# ----------------------------------------------------------------------
# project model + one-hop function index
# ----------------------------------------------------------------------


@dataclass
class FunctionInfo:
    name: str  # bare function / method name
    module_rel: str
    lineno: int
    node: ast.AST
    blocking: list = field(default_factory=list)  # [(line, reason)] direct blockers


class Project:
    """The file set under analysis plus package-wide derived indexes."""

    def __init__(self, root: str, files: list[SourceFile]):
        self.root = root
        self.files = files
        self._fn_index: dict[str, list[FunctionInfo]] | None = None

    def by_rel(self, rel: str) -> SourceFile | None:
        for f in self.files:
            if f.rel == rel or f.rel.endswith("/" + rel):
                return f
        return None

    @property
    def function_index(self) -> dict[str, list[FunctionInfo]]:
        """bare name -> definitions across the project, with each body's
        direct blocking calls precomputed (the one-hop expansion table)."""
        if self._fn_index is None:
            from kaspa_tpu.analysis.blocking import direct_blocking_calls

            index: dict[str, list[FunctionInfo]] = {}
            for f in self.files:
                for node in ast.walk(f.tree):
                    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        info = FunctionInfo(
                            node.name, f.rel, node.lineno, node,
                            blocking=direct_blocking_calls(node),
                        )
                        index.setdefault(node.name, []).append(info)
            self._fn_index = index
        return self._fn_index

    def resolve_call(self, name: str) -> FunctionInfo | None:
        """One-hop resolution by bare name: unique project-wide definition
        or nothing (ambiguous names are never expanded — precision over
        recall; the direct-call check still covers their bodies)."""
        infos = self.function_index.get(name)
        if infos is not None and len(infos) == 1:
            return infos[0]
        return None


def load_file(path: str, root: str) -> SourceFile | None:
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError:
        return None
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return SourceFile(path, rel, text, tree, _parse_pragmas(text))


def collect_files(paths: list[str], root: str) -> list[SourceFile]:
    seen: set[str] = set()
    out: list[SourceFile] = []
    for p in paths:
        if os.path.isfile(p):
            candidates = [p]
        else:
            candidates = []
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
                candidates.extend(
                    os.path.join(dirpath, fn) for fn in sorted(filenames) if fn.endswith(".py")
                )
        for c in candidates:
            c = os.path.abspath(c)
            if c in seen:
                continue
            seen.add(c)
            sf = load_file(c, root)
            if sf is not None:
                out.append(sf)
    return out


# ----------------------------------------------------------------------
# the run loop
# ----------------------------------------------------------------------


def run_project(paths: list[str], root: str | None = None) -> dict:
    """Lint ``paths``; returns the LINT.json document shape:

    {"findings": [...], "suppressed": [...], "counts": {...},
     "files": N, "ok": bool}

    ``ok`` is False iff any active finding remains — including ``pragma``
    findings for allow() lines missing a justification.
    """
    root = root or os.getcwd()
    files = collect_files(paths, root)
    project = Project(root, files)

    active: list[Finding] = []
    suppressed: list[Finding] = []
    for f in files:
        raised: list[Finding] = []
        for spec in CHECKERS.values():
            raised.extend(spec.fn(project, f))
        used_pragmas: set[int] = set()
        for finding in raised:
            pragma = f.allow_for(finding.line, finding.checker)
            if pragma is not None and pragma.justification:
                finding.justification = pragma.justification
                used_pragmas.add(pragma.line)
                suppressed.append(finding)
            else:
                active.append(finding)
        # pragma hygiene: every allow() must carry a justification.  (An
        # allow() that matches nothing is harmless — checkers evolve — but
        # a silent one is an undocumented hole in the gate.)
        for p in f.pragmas:
            if not p.justification:
                active.append(
                    Finding(
                        f.rel, p.line, "pragma",
                        f"allow({', '.join(p.checkers)}) carries no justification "
                        "(write `# graftlint: allow(<id>) -- <why>`)",
                    )
                )

    active.sort(key=Finding.key)
    suppressed.sort(key=Finding.key)
    counts: dict[str, int] = {}
    for finding in active:
        counts[finding.checker] = counts.get(finding.checker, 0) + 1
    return {
        "tool": "graftlint",
        "root": os.path.basename(os.path.abspath(root)),
        "files": len(files),
        "checkers": sorted(CHECKERS),
        "counts": counts,
        "findings": [x.as_dict() for x in active],
        "suppressed": [x.as_dict() for x in suppressed],
        "ok": not active,
    }
