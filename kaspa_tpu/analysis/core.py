"""graftlint core: findings, pragmas, the checker registry, project model.

Checkers are pure functions over parsed sources: ``check(project, file)
-> list[Finding]``.  The Project owns the file set and a lazily built
package-wide function index (the one-hop call graph the blocking checker
expands through).  Pragma handling lives here so every checker inherits
the same suppression semantics:

    x = threading.Lock()  # graftlint: allow(raw-lock) -- leaf metric guard

    # graftlint: allow(blocking-under-lock) -- cold path, bounded 50ms
    with self._mu:
        time.sleep(0.05)

A pragma suppresses matching findings on its own line; a pragma on a
comment-only line covers the next source line.  The justification (text
after ``--``/``—``) is mandatory: an allow() without one produces a
``pragma`` finding that cannot itself be suppressed.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

SEVERITY_ERROR = "error"
SEVERITY_WARN = "warn"

_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*allow\(\s*([a-z0-9_\-]+(?:\s*,\s*[a-z0-9_\-]+)*)\s*\)"
    r"\s*(?:(?:--|—|–)\s*(.*?))?\s*$"
)


@dataclass
class Finding:
    path: str  # repo-relative, stable across machines
    line: int
    checker: str
    message: str
    severity: str = SEVERITY_ERROR
    justification: str = ""  # filled when a pragma suppresses this finding

    def key(self) -> tuple:
        return (self.path, self.line, self.checker, self.message)

    def as_dict(self) -> dict:
        out = {
            "path": self.path,
            "line": self.line,
            "checker": self.checker,
            "severity": self.severity,
            "message": self.message,
        }
        if self.justification:
            out["justification"] = self.justification
        return out

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"


@dataclass
class _Pragma:
    line: int
    checkers: tuple[str, ...]
    justification: str
    covers_next: bool  # comment-only line: applies to the following line


@dataclass
class SourceFile:
    path: str  # absolute
    rel: str  # relative to the lint root (posix separators)
    text: str
    tree: ast.AST
    pragmas: list[_Pragma] = field(default_factory=list)
    _spans: list[tuple[int, int]] | None = None

    @property
    def lines(self) -> list[str]:
        return self.text.splitlines()

    @property
    def stmt_spans(self) -> list[tuple[int, int]]:
        """(start, end) line spans of statements, for pragma coverage on
        multi-line statements.  Simple statements span their whole
        extent; compound statements (def/class/if/with/...) span only
        their *header* — decorators through the line before the first
        body statement — so a pragma above a decorated def covers the
        def, never the body."""
        if self._spans is None:
            spans = []
            for node in ast.walk(self.tree):
                if not isinstance(node, ast.stmt) or node.end_lineno is None:
                    continue
                start = node.lineno
                decos = getattr(node, "decorator_list", [])
                if decos:
                    start = min(start, min(d.lineno for d in decos))
                body = getattr(node, "body", None)
                if isinstance(body, list) and body and isinstance(body[0], ast.stmt):
                    end = max(start, body[0].lineno - 1)  # header only
                else:
                    end = node.end_lineno
                if end > start:  # single-line statements use exact match
                    spans.append((start, end))
            self._spans = spans
        return self._spans

    def _span_containing(self, line: int) -> tuple[int, int] | None:
        best = None
        for s, e in self.stmt_spans:
            if s <= line <= e and (best is None or (e - s) < (best[1] - best[0])):
                best = (s, e)
        return best

    def allow_for(self, line: int, checker: str) -> _Pragma | None:
        """The pragma suppressing ``checker`` at ``line``, if any.

        Exact-line and comment-above semantics as v1, widened to
        multi-line statements: a pragma anywhere on a statement's span
        (e.g. on the closing paren of a wrapped call, or on the comment
        line above a decorated def) covers findings anchored to any line
        of that same statement's span."""
        for p in self.pragmas:
            if checker not in p.checkers and "all" not in p.checkers:
                continue
            if p.line == line or (p.covers_next and p.line + 1 == line):
                return p
            span = self._span_containing(p.line)
            if span is None and p.covers_next:
                span = self._span_containing(p.line + 1)
            if span is not None and span[0] <= line <= span[1]:
                return p
        return None


def _parse_pragmas(text: str) -> list[_Pragma]:
    out = []
    for i, raw in enumerate(text.splitlines(), start=1):
        m = _PRAGMA_RE.search(raw)
        if m is None:
            continue
        ids = tuple(s.strip() for s in m.group(1).split(","))
        just = (m.group(2) or "").strip()
        covers_next = raw.strip().startswith("#")
        out.append(_Pragma(i, ids, just, covers_next))
    return out


# ----------------------------------------------------------------------
# checker registry
# ----------------------------------------------------------------------

CHECKERS: dict[str, "CheckerSpec"] = {}
PROJECT_CHECKERS: dict[str, "CheckerSpec"] = {}


@dataclass
class CheckerSpec:
    id: str
    description: str
    fn: object  # (project, file) -> list[Finding]
    gated: bool = False  # only runs when project.options[id] is truthy


def register_checker(checker_id: str, description: str):
    def deco(fn):
        CHECKERS[checker_id] = CheckerSpec(checker_id, description, fn)
        return fn

    return deco


def register_project_checker(checker_id: str, description: str, gated: bool = False):
    """A checker that runs ONCE over the whole project — ``fn(project) ->
    list[Finding]`` — instead of per file (the kernel-shape audit, the
    env-knob catalog).  ``gated`` checkers only run when explicitly
    enabled via ``Project.options[checker_id]`` (they may import heavy
    runtime dependencies like jax)."""

    def deco(fn):
        PROJECT_CHECKERS[checker_id] = CheckerSpec(checker_id, description, fn, gated=gated)
        return fn

    return deco


# ----------------------------------------------------------------------
# project model + the whole-program call graph (v2 engine)
# ----------------------------------------------------------------------


class Project:
    """The file set under analysis plus package-wide derived indexes."""

    def __init__(self, root: str, files: list[SourceFile], options: dict | None = None):
        self.root = root
        self.files = files
        self.options = options or {}
        self._callgraph = None

    def by_rel(self, rel: str) -> SourceFile | None:
        for f in self.files:
            if f.rel == rel or f.rel.endswith("/" + rel):
                return f
        return None

    @property
    def callgraph(self):
        """The module-qualified call graph with fixpoint may-block /
        may-raise facts (built once per run, shared by every checker)."""
        if self._callgraph is None:
            from kaspa_tpu.analysis.callgraph import CallGraph

            self._callgraph = CallGraph(self.files)
        return self._callgraph


def load_file(path: str, root: str) -> SourceFile | None:
    with open(path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError:
        return None
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return SourceFile(path, rel, text, tree, _parse_pragmas(text))


def collect_files(paths: list[str], root: str) -> list[SourceFile]:
    seen: set[str] = set()
    out: list[SourceFile] = []
    for p in paths:
        if os.path.isfile(p):
            candidates = [p]
        else:
            candidates = []
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
                candidates.extend(
                    os.path.join(dirpath, fn) for fn in sorted(filenames) if fn.endswith(".py")
                )
        for c in candidates:
            c = os.path.abspath(c)
            if c in seen:
                continue
            seen.add(c)
            sf = load_file(c, root)
            if sf is not None:
                out.append(sf)
    return out


# ----------------------------------------------------------------------
# the run loop
# ----------------------------------------------------------------------


def run_project(paths: list[str], root: str | None = None, options: dict | None = None) -> dict:
    """Lint ``paths``; returns the LINT.json document shape:

    {"engine": "v2", "findings": [...], "suppressed": [...],
     "counts": {...}, "files": N, "callgraph": {...}, "ok": bool}

    ``ok`` is False iff any active finding remains — including ``pragma``
    findings for allow() lines missing a justification.  ``options``
    enables gated project-level checkers (``{"kernel-shape": True}``) and
    carries checker configuration.
    """
    root = root or os.getcwd()
    files = collect_files(paths, root)
    project = Project(root, files, options=options)
    by_rel = {f.rel: f for f in files}

    active: list[Finding] = []
    suppressed: list[Finding] = []

    def _file_findings(f: SourceFile, raised: list[Finding]) -> None:
        for finding in raised:
            pragma = f.allow_for(finding.line, finding.checker)
            if pragma is not None and pragma.justification:
                finding.justification = pragma.justification
                suppressed.append(finding)
            else:
                active.append(finding)

    for f in files:
        raised: list[Finding] = []
        for spec in CHECKERS.values():
            raised.extend(spec.fn(project, f))
        _file_findings(f, raised)
        # pragma hygiene: every allow() must carry a justification.  (An
        # allow() that matches nothing is harmless — checkers evolve — but
        # a silent one is an undocumented hole in the gate.)
        for p in f.pragmas:
            if not p.justification:
                active.append(
                    Finding(
                        f.rel, p.line, "pragma",
                        f"allow({', '.join(p.checkers)}) carries no justification "
                        "(write `# graftlint: allow(<id>) -- <why>`)",
                    )
                )

    # project-level checkers run once; their findings still honor pragmas
    # when anchored to a file in the lint set
    sections: dict[str, object] = {}
    for spec in PROJECT_CHECKERS.values():
        if spec.gated and not project.options.get(spec.id):
            continue
        raised = spec.fn(project)
        if isinstance(raised, tuple):  # (findings, report-section payload)
            raised, payload = raised
            sections[spec.id.replace("-", "_")] = payload
        for finding in raised:
            f = by_rel.get(finding.path)
            if f is not None:
                pragma = f.allow_for(finding.line, finding.checker)
                if pragma is not None and pragma.justification:
                    finding.justification = pragma.justification
                    suppressed.append(finding)
                    continue
            active.append(finding)

    active.sort(key=Finding.key)
    suppressed.sort(key=Finding.key)
    counts: dict[str, int] = {}
    for finding in active:
        counts[finding.checker] = counts.get(finding.checker, 0) + 1
    report = {
        "tool": "graftlint",
        "engine": "v2",
        "root": os.path.basename(os.path.abspath(root)),
        "files": len(files),
        "checkers": sorted(set(CHECKERS) | set(PROJECT_CHECKERS)),
        "counts": counts,
        "callgraph": project.callgraph.stats() if project._callgraph is not None else None,
        "findings": [x.as_dict() for x in active],
        "suppressed": [x.as_dict() for x in suppressed],
        "ok": not active,
    }
    report.update(sections)
    return report
